"""GPT-2 345M step profile capture (r3 weak #2: "no profile artifact"
— this script records one with the repo's own merged-timeline
profiler).

Captures a few bench-config GPT-2 train steps under
paddle_tpu.profiler.Profiler (host RecordEvents + jax/XLA device trace
folded into ONE chrome trace), writes the trace next to this script,
and prints a JSON summary of where the non-GEMM time goes — the
evidence behind the K-geometry ceiling argument (gemm_probe.py gives
the GEMM side).

Usage: python benchmarks/profile_gpt2.py [--steps 3]
Output: benchmarks/artifacts/gpt2_step_trace.json (chrome://tracing /
perfetto loadable) + one JSON summary line on stdout.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root, for paddle_tpu

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--out", default="/tmp/gpt2_step_trace.json",
                    help="full chrome trace (large — not committed)")
    ap.add_argument("--summary", default=os.path.join(
        os.path.dirname(__file__), "artifacts",
        "gpt2_step_summary.json"))
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.optimizer as optim
    import paddle_tpu.profiler as profiler
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    paddle.seed(0)
    if on_tpu:  # the bench.py gpt2_345m config, verbatim
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024,
                        num_layers=24, num_heads=16, ffn_hidden=4096,
                        max_seq_len=1024, dropout=0.0, remat=False,
                        use_flash_attention=True, scan_unroll=24)
        batch, seq = 4, 1024
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, ffn_hidden=256, max_seq_len=128,
                        dropout=0.0, remat=False,
                        use_flash_attention=False)
        batch, seq = 4, 128
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model = amp.decorate(model, level="O2", dtype="bfloat16")
    opt = optim.AdamW(learning_rate=1e-4,
                      parameters=model.parameters(),
                      weight_decay=0.01, multi_precision=on_tpu)
    step = TrainStepCompiler(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                          (batch, seq)).astype(np.int32))
    step(ids, labels).item()  # compile outside the trace

    prof = profiler.Profiler(python_tracer=False)
    prof.start()
    for _ in range(args.steps):
        with profiler.RecordEvent("train_step"):
            loss = step(ids, labels)
        loss.item()
        prof.step()
    prof.stop()
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    prof.export(args.out)

    # summarize the DEVICE timeline. The merged export folds several
    # profiler planes in as pid>=1000; one of them is jax's host
    # python-frame plane. Classify planes by content: a DEVICE plane
    # is one where most duration sits in XLA-op-shaped names
    # (while/fusion/convolution/jit_.../closed_call/...).
    import re

    with open(args.out) as f:
        events = json.load(f)["traceEvents"]
    xla_re = re.compile(
        r"^(while|fusion|copy|dot|conv|bitcast|add|mult|sub|div|"
        r"reduce|broadcast|transpose|dynamic|closed_call|call|jit_|"
        r"scatter|gather|select|compare|tuple|param|slice|concat|"
        r"rsqrt|exp|log|custom-call|all-|collective|iota|pad|rng|"
        r"cholesky|sort|convert|negate|power|maximum|minimum|tanh)")
    by_pid = collections.defaultdict(list)
    for e in events:
        if e.get("pid", 0) >= 1000 and e.get("dur", 0) > 0:
            by_pid[e["pid"]].append(e)
    device_events = []
    for pid, evs in by_pid.items():
        tot = sum(e["dur"] for e in evs)
        xla = sum(e["dur"] for e in evs
                  if xla_re.match(e["name"].lower()))
        if tot > 0 and xla / tot > 0.5:
            device_events.extend(evs)

    envelope_us = sum(e["dur"] for e in device_events
                      if e.get("name", "").startswith("jit_"))
    op_events = [e for e in device_events
                 if not e["name"].isdigit()          # thread-lane rows
                 and not e["name"].startswith("jit_")]  # step envelope
    bucket = collections.Counter()
    top_ops = collections.Counter()
    for e in op_events:
        name = e["name"]
        low = name.lower()
        top_ops[name.split("(")[0][:48]] += e["dur"]
        if low.startswith("while"):
            # the transformer layer stack is a lax.scan — fwd and bwd
            # each lower to one while op; per-layer ops live inside
            bucket["layer-scan (fwd+bwd bodies)"] += e["dur"]
        elif any(t in low for t in ("dot", "matmul", "gemm", "conv",
                                    "einsum")):
            bucket["gemm/conv"] += e["dur"]
        elif "fusion" in low:
            bucket["fusion (elementwise/reduce)"] += e["dur"]
        elif any(t in low for t in ("copy", "transpose", "reshape",
                                    "bitcast", "dynamic-update",
                                    "dynamic_update")):
            bucket["data-movement"] += e["dur"]
        elif low.startswith(("closed_call", "call")):
            bucket["called computations"] += e["dur"]
        else:
            bucket["other"] += e["dur"]
    total = sum(bucket.values()) or 1
    summary = {
        "trace": args.out,
        "steps": args.steps,
        "per_step_device_ms": round(envelope_us / 1e3 / args.steps, 2)
        if envelope_us else None,
        "opcount_device": len(op_events),
        "breakdown_pct": {k: round(100.0 * v / total, 1)
                          for k, v in bucket.most_common()},
        "top_ops_ms": {k: round(v / 1e3, 2)
                       for k, v in top_ops.most_common(15)},
        "note": "open the full trace in perfetto for the merged "
                "host+device timeline",
    }
    os.makedirs(os.path.dirname(args.summary), exist_ok=True)
    with open(args.summary, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
