"""ResNet-50 step profile capture (r5: the conv-side analog of
profile_gpt2.py — VERDICT r4 weak #3 asked for this artifact).

Captures bench-config ResNet-50 train steps under the merged-timeline
profiler and writes a device-op breakdown summary.

Usage: python benchmarks/profile_resnet50.py [--steps 3]
Output: benchmarks/artifacts/resnet50_step_summary.json
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def summarize(trace_path, steps):
    """Device-plane breakdown (shared with profile_gpt2 methodology)."""
    xla_re = re.compile(
        r"^(while|fusion|copy|dot|conv|bitcast|add|mult|sub|div|"
        r"reduce|broadcast|transpose|dynamic|closed_call|call|jit_|"
        r"scatter|gather|select|compare|tuple|param|slice|concat|"
        r"rsqrt|exp|log|custom-call|all-|collective|iota|pad|rng|"
        r"cholesky|sort|convert|negate|power|maximum|minimum|tanh)")
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    by_pid = collections.defaultdict(list)
    for e in events:
        if e.get("pid", 0) >= 1000 and e.get("dur", 0) > 0:
            by_pid[e["pid"]].append(e)
    device_events = []
    for pid, evs in by_pid.items():
        # classify on non-digit names only: step-number envelope rows
        # ("0","1","2" with whole-step durations) would dilute the
        # XLA-op duration share below threshold on conv traces
        named = [e for e in evs if not e["name"].isdigit()]
        tot = sum(e["dur"] for e in named)
        xla = sum(e["dur"] for e in named
                  if xla_re.match(e["name"].lower()))
        if tot > 0 and xla / tot > 0.5:
            device_events.extend(evs)
    envelope_us = sum(e["dur"] for e in device_events
                      if e.get("name", "").startswith("jit_"))
    op_events = [e for e in device_events
                 if not e["name"].isdigit()
                 and not e["name"].startswith("jit_")]
    bucket = collections.Counter()
    top_ops = collections.Counter()
    for e in op_events:
        name = e["name"]
        low = name.lower()
        top_ops[name.split("(")[0][:48]] += e["dur"]
        if any(t in low for t in ("conv", "dot", "matmul", "gemm",
                                  "einsum")):
            bucket["conv/gemm (incl fused)"] += e["dur"]
        elif "fusion" in low:
            bucket["fusion (elementwise/reduce)"] += e["dur"]
        elif any(t in low for t in ("copy", "transpose", "reshape",
                                    "bitcast", "dynamic-update",
                                    "dynamic_update")):
            bucket["data-movement"] += e["dur"]
        elif low.startswith(("closed_call", "call")):
            bucket["called computations"] += e["dur"]
        else:
            bucket["other"] += e["dur"]
    total = sum(bucket.values()) or 1
    return {
        "trace": trace_path,
        "steps": steps,
        "per_step_device_ms": round(envelope_us / 1e3 / steps, 2)
        if envelope_us else None,
        "opcount_device": len(op_events),
        "breakdown_pct": {k: round(100.0 * v / total, 1)
                          for k, v in bucket.most_common()},
        "top_ops_ms": {k: round(v / 1e3, 2)
                       for k, v in top_ops.most_common(15)},
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--out", default="/tmp/resnet50_step_trace.json")
    ap.add_argument("--summary", default=os.path.join(
        os.path.dirname(__file__), "artifacts",
        "resnet50_step_summary.json"))
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    import paddle_tpu.profiler as profiler
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.vision.models import resnet50

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    paddle.seed(0)
    batch = 128 if on_tpu else 2
    size = 224 if on_tpu else 32
    net = resnet50()
    if on_tpu:
        net = amp.decorate(net, level="O2", dtype="bfloat16")
    ce = nn.CrossEntropyLoss()
    opt = optim.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=net.parameters(),
                         multi_precision=on_tpu)
    step = TrainStepCompiler(net, opt, lambda o, y: ce(o, y))
    rng = np.random.RandomState(0)
    import jax.numpy as jnp

    x = paddle.to_tensor(rng.randn(batch, 3, size, size)
                         .astype(np.float32))
    if on_tpu:
        x._value = x._value.astype(jnp.bfloat16)
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int64))
    step(x, y).item()  # compile outside the trace

    prof = profiler.Profiler(python_tracer=False)
    prof.start()
    for _ in range(args.steps):
        with profiler.RecordEvent("train_step"):
            loss = step(x, y)
        loss.item()
        prof.step()
    prof.stop()
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    prof.export(args.out)
    summary = summarize(args.out, args.steps)
    os.makedirs(os.path.dirname(args.summary), exist_ok=True)
    with open(args.summary, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
