"""GPT-2 345M config-sweep harness (r5 perf round).

Runs the exact bench.py GPT-2 methodology (median-of-5 windows,
.item() syncs) over a list of config variants passed on the CLI, so
candidate optimizations are measured with the same instrument that
records BENCH_r{N}.json.

usage: python benchmarks/exp_gpt2.py '{"name":"ctl"}' \
           '{"name":"u24","scan_unroll":24}' ...
Each arg is a JSON dict: model-config overrides + optional "batch",
"steps", "warmup", "accum".
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def run_variant(spec):
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    spec = dict(spec)
    name = spec.pop("name")
    batch = spec.pop("batch", 4)
    steps = spec.pop("steps", 20)
    warmup = spec.pop("warmup", 3)
    windows = spec.pop("windows", 5)
    accum = spec.pop("accum", 1)
    paddle.seed(0)
    base = dict(vocab_size=50304, hidden_size=1024, num_layers=24,
                num_heads=16, ffn_hidden=4096, max_seq_len=1024,
                dropout=0.0, remat=False, use_flash_attention=True)
    base.update(spec)
    cfg = GPTConfig(**base)
    seq = 1024
    model = GPTForCausalLM(cfg)
    model = amp.decorate(model, level="O2", dtype="bfloat16")
    opt = optim.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                      weight_decay=0.01, multi_precision=True)
    step = TrainStepCompiler(model, opt, loss_fn=None,
                             accumulate_steps=accum)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                          (batch, seq)).astype(np.int32))
    t0 = time.perf_counter()
    for _ in range(warmup):
        loss = step(ids, labels)
    first = float(loss.item())
    compile_s = time.perf_counter() - t0
    dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, labels)
        last = float(loss.item())
        dts.append((time.perf_counter() - t0) / steps)
    dt = float(np.median(dts))
    assert np.isfinite(last) and last < first, (name, first, last)
    toks = batch * seq / dt
    n = sum(int(np.prod(p.shape)) for p in model.parameters())
    mfu = 6 * n * batch * seq / dt / 197e12
    rec = {"name": name, "tok_s": round(toks, 1),
           "ms_step": round(dt * 1e3, 2), "mfu": round(mfu, 4),
           "compile_s": round(compile_s, 1),
           "spread_ms": [round(d * 1e3, 2) for d in dts]}
    print("[exp]", json.dumps(rec), flush=True)
    return rec


def main():
    recs = []
    for arg in sys.argv[1:]:
        spec = json.loads(arg)
        try:
            recs.append(run_variant(spec))
        except Exception as e:
            print("[exp]", json.dumps({"name": spec.get("name"),
                                       "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
    print(json.dumps(recs))


if __name__ == "__main__":
    main()
