"""Bench-trail regression gate (ISSUE 16): compare the newest
BENCH_r*.json round against the newest prior round, per config, with
noise bands derived from each record's own window_spread.

The repo-root BENCH_r*.json files are the bench trail — one record
per optimization round, each carrying `parsed.extra.<config>.value`
(throughput, higher is better) and `window_spread` (the per-window
wall times bench.py measured the median from). Until now nothing
read them: a plateau or a regression between rounds was invisible to
any gate. This module closes that loop:

    python benchmarks/regress.py                # newest vs prior
    python benchmarks/regress.py --current f.json   # f vs newest
    python bench.py --baseline                  # live run vs trail

Noise bands, not fixed tolerances: a config's band is the relative
spread of its measurement windows — (max-min)/median of
window_spread, the same five windows the median throughput came from
— taken as the max of the two rounds being compared and clamped to
[BAND_FLOOR, BAND_CAP]. A config that measures noisily (the
mnist_lenet dispatch-latency probe, the single-core pipeline config)
gets a wide band from its own data instead of a hand-maintained
volatile list; a tight config (resnet50) is gated at the floor.

Exit codes (the op_bench gate convention): 0 clean, 2 on any
regression beyond band / config missing from the current round / bad
input. Stdlib only — the gate must run anywhere the JSON files do.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIL_GLOB = "BENCH_r*.json"
# relative noise-band clamp: never gate tighter than 5% (timer
# jitter on a quiet config), never looser than 50% (a halved
# throughput fails no matter how noisy the config measures)
BAND_FLOOR = 0.05
BAND_CAP = 0.5

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def rel_spread(entry):
    """Relative window spread of one config entry: (max-min)/median
    of its window_spread wall times. None when the record carries
    fewer than two windows (no spread to derive a band from)."""
    ws = [float(w) for w in (entry.get("window_spread") or [])
          if w and float(w) > 0]
    if len(ws) < 2:
        return None
    ws.sort()
    med = ws[len(ws) // 2]
    return (ws[-1] - ws[0]) / med if med > 0 else None


def noise_band(base_entry, cur_entry, floor=BAND_FLOOR, cap=BAND_CAP):
    """The comparison band for one config: the WIDER of the two
    rounds' relative spreads (either side measuring noisily makes
    the delta unreadable), clamped to [floor, cap]."""
    spreads = [s for s in (rel_spread(base_entry),
                           rel_spread(cur_entry)) if s is not None]
    band = max(spreads) if spreads else floor
    return min(cap, max(floor, band))


def load_trail(root=None):
    """The round records on disk, sorted by round number, keeping
    only rounds that carry a per-config `parsed.extra` dict (early
    rounds predate it). Raises ValueError on unreadable JSON — the
    exit-2 contract."""
    root = root or REPO_ROOT
    out = []
    for path in sorted(glob.glob(os.path.join(root, TRAIL_GLOB))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON ({e})")
        m = _ROUND_RE.search(path)
        n = int(rec.get("n", m.group(1) if m else 0))
        extra = (rec.get("parsed") or {}).get("extra")
        if isinstance(extra, dict) and extra:
            out.append({"n": n, "path": path, "extra": extra})
    out.sort(key=lambda r: r["n"])
    return out


def _configs(extra):
    """The gateable config entries of one round: dict-valued extra
    entries with a numeric throughput value (the extra dict also
    carries non-config sections like `perf` and `telemetry`)."""
    out = {}
    for k, v in extra.items():
        if isinstance(v, dict) and isinstance(
                v.get("value"), (int, float)):
            out[k] = v
    return out


def compare(base_extra, cur_extra, floor=BAND_FLOOR, cap=BAND_CAP):
    """Per-config verdicts comparing `cur` against `base` (both
    `parsed.extra` dicts). Statuses: ok / regression (value fell
    below base*(1-band)) / missing (config vanished — the silent
    failure mode a gate exists for) / new (no baseline yet)."""
    base_cfg = _configs(base_extra)
    cur_cfg = _configs(cur_extra)
    rows = []
    for name in sorted(set(base_cfg) | set(cur_cfg)):
        b, c = base_cfg.get(name), cur_cfg.get(name)
        if b is None:
            rows.append({"config": name, "status": "new",
                         "current": c["value"]})
            continue
        if c is None:
            rows.append({"config": name, "status": "missing",
                         "baseline": b["value"]})
            continue
        band = noise_band(b, c, floor=floor, cap=cap)
        ratio = (c["value"] / b["value"]) if b["value"] else 1.0
        status = "regression" if ratio < 1.0 - band else "ok"
        rows.append({"config": name, "status": status,
                     "baseline": b["value"], "current": c["value"],
                     "ratio": round(ratio, 4),
                     "band": round(band, 4),
                     "unit": c.get("unit") or b.get("unit")})
    return rows


def gate(rows):
    """rc for a comparison: 2 when any row regressed or vanished."""
    return 2 if any(r["status"] in ("regression", "missing")
                    for r in rows) else 0


def _render(rows, base_label, cur_label):
    out = [f"bench regression gate: {cur_label} vs {base_label}"]
    for r in rows:
        s = r["status"]
        if s == "new":
            out.append(f"  NEW        {r['config']}: "
                       f"{r['current']} (no baseline round)")
        elif s == "missing":
            out.append(f"  MISSING    {r['config']}: was "
                       f"{r['baseline']} — absent from current round")
        else:
            tag = "REGRESSION" if s == "regression" else "OK"
            out.append(
                f"  {tag:<10s} {r['config']}: {r['baseline']} -> "
                f"{r['current']} {r.get('unit') or ''} "
                f"(x{r['ratio']}, band {r['band']})")
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="benchmarks/regress.py",
        description="Gate the newest bench round against the prior "
                    "one with window_spread-derived noise bands.")
    p.add_argument("--root", default=None,
                   help="directory holding the BENCH_r*.json trail "
                        "(default: the repo root)")
    p.add_argument("--current", default=None,
                   help="compare THIS record (a bench.py JSON "
                        "output) against the newest trail round, "
                        "instead of newest-vs-prior")
    p.add_argument("--floor", type=float, default=BAND_FLOOR,
                   help=f"noise-band floor (default {BAND_FLOOR})")
    p.add_argument("--cap", type=float, default=BAND_CAP,
                   help=f"noise-band cap (default {BAND_CAP})")
    p.add_argument("--json", action="store_true",
                   help="emit the per-config verdict rows as JSON")
    args = p.parse_args(argv)
    try:
        trail = load_trail(args.root)
        if args.current:
            if not trail:
                raise ValueError(
                    "no BENCH_r*.json rounds with parsed.extra to "
                    "compare against")
            with open(args.current) as f:
                cur_rec = json.load(f)
            cur_extra = (cur_rec.get("parsed") or {}).get("extra") \
                or cur_rec.get("extra")
            if not isinstance(cur_extra, dict):
                raise ValueError(
                    f"{args.current}: no parsed.extra/extra section")
            base = trail[-1]
            base_label, cur_label = (f"r{base['n']:02d}",
                                     args.current)
        else:
            if len(trail) < 2:
                raise ValueError(
                    "need at least two BENCH_r*.json rounds with "
                    "parsed.extra (newest is compared to prior)")
            base, cur = trail[-2], trail[-1]
            cur_extra = cur["extra"]
            base_label, cur_label = (f"r{base['n']:02d}",
                                     f"r{cur['n']:02d}")
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    rows = compare(base["extra"], cur_extra,
                   floor=args.floor, cap=args.cap)
    if args.json:
        json.dump({"base": base_label, "current": cur_label,
                   "rows": rows}, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        print(_render(rows, base_label, cur_label))
    rc = gate(rows)
    if rc:
        print("regression beyond noise band — see rows above",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
