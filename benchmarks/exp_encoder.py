"""BERT/ERNIE-side experiments (r5): attention blocks at S=512 and
the recorded-config bench numbers, using bench.py's own methodology."""
from __future__ import annotations

import functools
import json
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def attn_sweep():
    import jax.numpy as jnp  # noqa
    from attn_bench import time_fwd_bwd
    from paddle_tpu.incubate.nn.attention_pallas import flash_attention

    B, H, S, D = 32, 12, 512, 64
    fwd_fl = 2 * 2 * B * H * S * S * D  # non-causal (BERT)
    tot_fl = fwd_fl * 3.5
    for bq, bk in [(512, 512), (256, 256), (512, 256), (128, 128)]:
        fn = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, False, 1.0 / np.sqrt(D), bq, bk)
        try:
            dt = time_fwd_bwd(fn, B, H, S, D, n=4)
            print("[enc]", json.dumps(
                {"attn": f"bq{bq}_bk{bk}", "ms": round(dt * 1e3, 3),
                 "tflops": round(tot_fl / dt / 1e12, 1)}), flush=True)
        except Exception as e:
            print("[enc]", json.dumps({"attn": f"bq{bq}_bk{bk}",
                                       "error": str(e)[:160]}), flush=True)


def bench_models():
    import bench

    for name, fn in (("bert", bench.bench_bert),
                     ("ernie", bench.bench_ernie)):
        try:
            r = fn(True)
            r.pop("window_spread", None)
            print("[enc]", json.dumps({name: r}), flush=True)
        except Exception as e:
            print("[enc]", json.dumps({name: f"{type(e).__name__}: {e}"}),
                  flush=True)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    attn_sweep()
    bench_models()
