"""Flash-attention kernel micro-benchmark (r5 perf round).

Times fwd+bwd of causal attention at the GPT-2 bench shape for:
  * the repo Pallas kernel (incubate/nn/attention_pallas.py) at a
    sweep of (block_q, block_k)
  * jax's reference TPU Pallas flash kernel (public jax library code)
  * XLA dense attention (the O(S^2)-memory fallback)

Methodology per the repo's corrected-probe rules (BASELINE.md r4):
device-get syncs (.block_until_ready lies on the tunnel backend),
serial chaining so XLA can't batch/elide iterations, and two loop
lengths so tunnel RTT cancels: t = (T(2n) - T(n)) / n.
"""
from __future__ import annotations

import functools
import json
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp


def time_fwd_bwd(attn_fn, B, H, S, D, n=8):
    rng = np.random.RandomState(0)
    q0 = jnp.asarray(rng.randn(B, H, S, D), jnp.bfloat16)
    k0 = jnp.asarray(rng.randn(B, H, S, D), jnp.bfloat16)
    v0 = jnp.asarray(rng.randn(B, H, S, D), jnp.bfloat16)

    def loss(q, k, v):
        o = attn_fn(q, k, v)
        return jnp.sum(o.astype(jnp.float32) * 1e-3)

    g = jax.value_and_grad(loss, argnums=(0, 1, 2))

    @functools.partial(jax.jit, static_argnums=2)
    def chain(q, k, length):
        def body(carry, _):
            qc, kc = carry
            l, (dq, dk, dv) = g(qc, kc, v0)
            # serial dependence: next iteration's inputs depend on this
            # iteration's grads in a way constant folding can't remove
            qc = q0 + (l.astype(jnp.bfloat16) * 1e-20) * dq
            kc = k0 + (l.astype(jnp.bfloat16) * 1e-20) * dk
            return (qc, kc), dv[0, 0, 0, 0]
        (qf, _), outs = jax.lax.scan(body, (q, k), None, length=length)
        return qf[0, 0, 0, 0] + jnp.sum(outs)

    def run(length):
        t0 = time.perf_counter()
        float(np.asarray(chain(q0, k0, length)))  # device-get sync
        return time.perf_counter() - t0

    run(n)       # compile n
    run(2 * n)   # compile 2n
    ts_n = min(run(n) for _ in range(3))
    ts_2n = min(run(2 * n) for _ in range(3))
    return (ts_2n - ts_n) / n


def main():
    B, H, S, D = 4, 16, 1024, 64
    # causal fwd ~2*2*B*H*S^2*D/2 FLOPs; bwd ~2.5x fwd
    fwd_fl = 2 * 2 * B * H * S * S * D * 0.5
    tot_fl = fwd_fl * 3.5
    results = {}

    from paddle_tpu.incubate.nn.attention_pallas import flash_attention

    for bq, bk in [(256, 256), (512, 512), (512, 256), (1024, 512),
                   (256, 512), (1024, 1024)]:
        name = f"repo_bq{bq}_bk{bk}"
        try:
            fn = lambda q, k, v: flash_attention(  # noqa: E731
                q, k, v, True, 1.0 / np.sqrt(D), bq, bk)
            dt = time_fwd_bwd(fn, B, H, S, D)
            results[name] = {"ms": round(dt * 1e3, 3),
                             "tflops": round(tot_fl / dt / 1e12, 1)}
        except Exception as e:
            results[name] = {"error": str(e)[:200]}
        print("[attn]", name, json.dumps(results[name]), flush=True)

    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_fa)

        fn = lambda q, k, v: jax_fa(  # noqa: E731
            q, k, v, causal=True, sm_scale=1.0 / float(np.sqrt(D)))
        dt = time_fwd_bwd(fn, B, H, S, D)
        results["jax_pallas"] = {"ms": round(dt * 1e3, 3),
                                 "tflops": round(tot_fl / dt / 1e12, 1)}
    except Exception as e:
        results["jax_pallas"] = {"error": str(e)[:200]}
    print("[attn] jax_pallas", json.dumps(results["jax_pallas"]),
          flush=True)

    def dense(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32)
        s = s / np.sqrt(D)
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    try:
        dt = time_fwd_bwd(dense, B, H, S, D)
        results["xla_dense"] = {"ms": round(dt * 1e3, 3),
                                "tflops": round(tot_fl / dt / 1e12, 1)}
    except Exception as e:
        results["xla_dense"] = {"error": str(e)[:200]}
    print("[attn] xla_dense", json.dumps(results["xla_dense"]),
          flush=True)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
