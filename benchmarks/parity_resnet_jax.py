"""Pure-JAX ResNet-50 parity benchmark (r3 weak #2: the "framework is
at raw-JAX parity" claim rested on an unrecorded probe — this is the
runnable record).

A from-scratch jax/lax ResNet-50 (NHWC, bf16 activations, fp32 BN
statistics, SGD+momentum fwd+bwd train step) with NO paddle_tpu imports
— an independent ceiling for what any framework gets out of XLA on this
chip at the same batch/shape. Compare its imgs/s with bench.py's
`resnet50` config: parity (within jitter) means the framework layer
adds no overhead; a gap means framework overhead to chase.

Usage: python benchmarks/parity_resnet_jax.py [--batch 128] [--steps 60]
Prints one JSON line.
"""
from __future__ import annotations

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

CFG50 = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
         (3, 512, 2048, 2)]  # (blocks, width, out, first-stride)


def _conv(x, w, stride=1):
    # bf16 in/out (no preferred_element_type: an f32 primal output
    # hands the conv transpose an f32 cotangent against bf16 operands,
    # which lax rejects)
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, scale, bias, training=True):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    var = jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - jnp.square(mean)
    y = (xf - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias
    return y.astype(x.dtype)


def _bottleneck(x, p, stride):
    idt = x
    y = jax.nn.relu(_bn(_conv(x, p["w1"]), p["s1"], p["b1"]))
    y = jax.nn.relu(_bn(_conv(y, p["w2"], stride), p["s2"], p["b2"]))
    y = _bn(_conv(y, p["w3"]), p["s3"], p["b3"])
    if "wd" in p:
        idt = _bn(_conv(x, p["wd"], stride), p["sd"], p["bd"])
    return jax.nn.relu(y + idt)


def init_params(rng):
    def conv_w(key, kh, kw, cin, cout):
        fan = kh * kw * cin
        return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
                * np.sqrt(2.0 / fan)).astype(jnp.bfloat16)

    keys = iter(jax.random.split(rng, 256))
    params = {"stem_w": conv_w(next(keys), 7, 7, 3, 64),
              "stem_s": jnp.ones(64), "stem_b": jnp.zeros(64)}
    cin = 64
    for si, (blocks, width, cout, stride0) in enumerate(CFG50):
        for bi in range(blocks):
            p = {}
            stride = stride0 if bi == 0 else 1
            p["w1"] = conv_w(next(keys), 1, 1, cin, width)
            p["w2"] = conv_w(next(keys), 3, 3, width, width)
            p["w3"] = conv_w(next(keys), 1, 1, width, cout)
            for t in ("1", "2", "3"):
                c = {"1": width, "2": width, "3": cout}[t]
                p[f"s{t}"] = jnp.ones(c)
                p[f"b{t}"] = jnp.zeros(c)
            if bi == 0:
                p["wd"] = conv_w(next(keys), 1, 1, cin, cout)
                p["sd"] = jnp.ones(cout)
                p["bd"] = jnp.zeros(cout)
            params[f"s{si}b{bi}"] = p
            cin = cout
    params["fc_w"] = (jax.random.normal(next(keys), (2048, 1000),
                                        jnp.float32) * 0.01
                      ).astype(jnp.bfloat16)
    params["fc_b"] = jnp.zeros(1000, jnp.float32)
    return params


def forward(params, x):
    y = jax.nn.relu(_bn(_conv(x, params["stem_w"], 2),
                        params["stem_s"], params["stem_b"]))
    y = jax.lax.reduce_window(
        y, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for si, (blocks, _, _, stride0) in enumerate(CFG50):
        for bi in range(blocks):
            y = _bottleneck(y, params[f"s{si}b{bi}"],
                            stride0 if bi == 0 else 1)
    y = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
    return y @ params["fc_w"].astype(jnp.float32) + params["fc_b"]


def loss_fn(params, x, labels):
    logits = forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                         axis=1))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def train_step(params, momentum, x, labels, lr=0.01, mu=0.9):
    loss, grads = jax.value_and_grad(loss_fn)(params, x, labels)

    def upd(p, m, g):
        m2 = mu * m + g.astype(jnp.float32)
        return (p.astype(jnp.float32) - lr * m2).astype(p.dtype), m2

    flat_p, tree = jax.tree_util.tree_flatten(params)
    flat_m = jax.tree_util.tree_leaves(momentum)
    flat_g = jax.tree_util.tree_leaves(grads)
    new = [upd(p, m, g) for p, m, g in zip(flat_p, flat_m, flat_g)]
    params = jax.tree_util.tree_unflatten(tree, [a for a, _ in new])
    momentum = jax.tree_util.tree_unflatten(tree, [b for _, b in new])
    return params, momentum, loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--windows", type=int, default=5)
    args = ap.parse_args()
    rng = np.random.RandomState(0)
    params = init_params(jax.random.PRNGKey(0))
    momentum = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    x = jnp.asarray(rng.randn(args.batch, 224, 224, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, (args.batch,)), jnp.int32)
    params, momentum, loss = train_step(params, momentum, x, labels)
    float(np.asarray(loss))  # compile + TRUE sync (device-get:
    # block_until_ready returns early on the tunnel backend —
    # see gemm_probe.py)
    dts = []
    for _ in range(args.windows):
        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, momentum, loss = train_step(params, momentum, x,
                                                labels)
        float(np.asarray(loss))
        dts.append((time.perf_counter() - t0) / args.steps)
    dt = float(np.median(dts))
    print(json.dumps({
        "metric": "pure_jax_resnet50_imgs_per_sec",
        "value": round(args.batch / dt, 1),
        "unit": "imgs/s",
        "batch": args.batch,
        "window_spread": [round(d, 6) for d in dts],
        "note": "independent raw-XLA ceiling; compare bench.py resnet50",
    }))


if __name__ == "__main__":
    main()
