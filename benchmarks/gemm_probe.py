"""K-geometry GEMM ceiling probe (r3 weak #2: the chip-ceiling defense
rested on unrecorded probe numbers — this is the runnable record).

Measures sustained bf16 matmul TF/s as a function of the contraction
dimension K with M=N fixed, using the same methodology BASELINE.md
cites: a chained-carry fori_loop inside one jit (so XLA cannot dead-code
or overlap host latency), D2H-synced, loop overhead differenced out via
a zero-work baseline loop.

Why K matters: the MXU pipeline amortizes weight-load over K. A
transformer's hidden-size GEMMs (K = 768/1024) cannot reach the
K>=4096 peak — this probe quantifies that gap on the current chip, and
with it the per-model ceiling (e.g. GPT-2 345M: hidden 1024 -> the
K=1024 row bounds tokens/s).

Usage: python benchmarks/gemm_probe.py [--mn 4096] [--iters 32]
Prints one JSON line per K plus a summary.
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _timed_loop(k, m_rows, target_s=0.25):
    """TF/s for the bf16 [M,K]@[K,K] matmul — the transformer layer
    geometry (M = batch*seq tokens, N = K = hidden). The carry IS the
    activation matrix (a_{i+1} = (a_i @ b) * const), so iterations are
    truly serial: earlier probe shapes let XLA hoist the matmul
    (scalar-scaled lhs commutes), shrink it (single-element reads,
    slice pushdown), or factor it (sum(A@B) = colsum(A)@rowsum(B)) —
    all observed on-chip as impossible TF/s readings.

    Timing: the loop runs at two lengths n and 2n and the per-iter
    time is (t_2n - t_n)/n, which cancels the host-tunnel RTT exactly;
    n is auto-sized so the loop body compute dwarfs RTT jitter.
    """
    a = jnp.asarray(np.random.RandomState(0).randn(m_rows, k),
                    jnp.bfloat16)
    b = jnp.asarray(
        np.random.RandomState(1).randn(k, k) / np.sqrt(k) * 0.5,
        jnp.bfloat16)
    flops = 2.0 * m_rows * k * k
    n = min(50000, max(64, int(target_s * 150e12 / flops)))

    def mk(iters):
        @jax.jit
        def chain(a, b):
            def body(_, carry):
                return ((carry @ b) * jnp.bfloat16(1.0009765625))

            return jax.lax.fori_loop(0, iters, body, a)

        return chain

    def run_sync(f):
        """device_get of one element is the only RELIABLE sync on the
        tunnel backend — block_until_ready returns early there
        (observed: loop length had no effect on 'blocked' wall time)."""
        t0 = time.perf_counter()
        np.asarray(f(a, b)[0, 0])
        return time.perf_counter() - t0

    c1, c2 = mk(n), mk(2 * n)
    run_sync(c1)   # compile
    run_sync(c2)
    t1s = [run_sync(c1) for _ in range(3)]
    t2s = [run_sync(c2) for _ in range(3)]
    dt = max(float(np.median(t2s)) - float(np.median(t1s)), 1e-9) / n
    return flops / dt / 1e12, dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--m-rows", type=int, default=4096,
                    help="token dimension M (batch*seq)")
    args = ap.parse_args()
    rows = []
    for k in (256, 512, 768, 1024, 2048, 4096, 8192):
        tfs, dt = _timed_loop(k, args.m_rows)
        rows.append({"K": k, "M": args.m_rows, "tflops": round(tfs, 1),
                     "step_ms": round(dt * 1e3, 3)})
        print(json.dumps(rows[-1]))
    peak = max(r["tflops"] for r in rows)
    k1024 = next(r["tflops"] for r in rows if r["K"] == 1024)
    frac = k1024 / peak
    print(json.dumps({
        "summary": "K-geometry GEMM sustained TF/s",
        "peak_tflops": peak,
        "k1024_tflops": k1024,
        "k1024_fraction_of_peak": round(frac, 3),
        "note": ("K=1024 GEMMs are geometry-bound; model ceilings "
                 "follow from the K=1024 row" if frac < 0.7 else
                 "K=1024 GEMMs run near peak: hidden-1024 models are "
                 "NOT GEMM-geometry-bound — profile the step "
                 "(profile_gpt2.py) for the real time sink"),
    }))


if __name__ == "__main__":
    main()
