"""Per-op micro-benchmark harness + regression record (r4 verdict
missing #5).

Parity target: paddle/fluid/operators/benchmark/op_tester.cc +
tools/ci_op_benchmark.sh — a config-driven per-op timing harness whose
JSON record lets the next round diff per-op performance instead of
discovering regressions at the model level.

Methodology (BASELINE.md r4 corrected-probe rules): ops are chained
serially inside one jitted lax.scan (XLA cannot batch or elide
iterations whose input depends on the previous output), timing uses
device-get syncs (block_until_ready lies on the tunnel backend), and
two scan lengths cancel the tunnel RTT: t = (T(2n) - T(n)) / n.

usage:
    python benchmarks/op_bench.py                  # run all, print
    python benchmarks/op_bench.py --save           # + write baseline
    python benchmarks/op_bench.py --check [--tol 0.25]
        # compare against the committed baseline; exit 1 on any op
        # slower than baseline*(1+tol) — the CI regression gate
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "artifacts",
                             "op_bench_baseline.json")


def jnp_sum_f32(a):
    import jax.numpy as jnp

    return jnp.sum(a.astype(jnp.float32))


def _chain_time(step_fn, init, n=16, reps=3, min_diff_s=0.03):
    """Serial-chain timing: median over `reps` of (T(2n)-T(n))/n.

    The chain length adapts upward until the measured difference
    clears the tunnel's RTT jitter (~tens of ms) — a fixed short chain
    under-resolves cheap ops into noise (or 0)."""
    import jax

    @functools.partial(jax.jit, static_argnums=1)
    def chain(x0, length):
        def body(c, _):
            return step_fn(c), None

        out, _ = jax.lax.scan(body, x0, None, length=length)
        # sync value must depend on EVERY element: reading one element
        # lets XLA slice the whole elementwise chain down to scalar
        # ops (BASELINE.md corrected-probe rules). The extra reduce is
        # identical at both lengths, so (T(2n)-T(n)) cancels it.
        return jax.tree_util.tree_map(
            lambda a: jnp_sum_f32(a), out)

    def run(length):
        t0 = time.perf_counter()
        out = chain(init, length)
        _ = [float(np.asarray(o)) for o in
             jax.tree_util.tree_leaves(out)]  # device-get sync
        return time.perf_counter() - t0

    while True:
        run(n)
        run(2 * n)
        diff = min(run(2 * n) for _ in range(2)) - min(
            run(n) for _ in range(2))
        if diff >= min_diff_s or n >= 4096:
            break
        n *= 4
    ts_n = [run(n) for _ in range(reps)]
    ts_2n = [run(2 * n) for _ in range(reps)]
    return max((float(np.median(ts_2n)) - float(np.median(ts_n))) / n,
               1e-9)


def _f32(rng, *shape):
    import jax.numpy as jnp

    return jnp.asarray(rng.randn(*shape), jnp.float32)


def _bf16(rng, *shape):
    import jax.numpy as jnp

    return jnp.asarray(rng.randn(*shape), jnp.bfloat16)


def build_ops():
    """name -> (init_carry, step_fn, work_dict). step_fn must be
    shape-preserving on the carry (serial chain)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    ops = {}

    # -- MXU ----------------------------------------------------------
    # abs() in every linear chain: without a nonlinearity XLA folds
    # the unrolled iterations ((x@W)*c chains precompute to one
    # effective matrix; affine elementwise chains fold to one op)
    w1 = _bf16(rng, 1024, 1024)
    ops["matmul_4096x1024x1024_bf16"] = (
        _bf16(rng, 4096, 1024),
        lambda x: jnp.abs(x @ w1) * jnp.bfloat16(0.001),
        {"flops": 2 * 4096 * 1024 * 1024})
    w2 = _bf16(rng, 4096, 4096)
    ops["matmul_4096x4096x4096_bf16"] = (
        _bf16(rng, 4096, 4096),
        lambda x: jnp.abs(x @ w2) * jnp.bfloat16(0.0001),
        {"flops": 2 * 4096 * 4096 * 4096})
    kw = _bf16(rng, 3, 3, 256, 256)
    ops["conv2d_3x3_56x56x256_bf16"] = (
        _bf16(rng, 32, 56, 56, 256),
        lambda x: jnp.abs(jax.lax.conv_general_dilated(
            x, kw, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")))
        * jnp.bfloat16(0.01),
        {"flops": 2 * 32 * 56 * 56 * 256 * 256 * 9})

    # -- VPU / HBM ----------------------------------------------------
    big = _f32(rng, 4096, 4096)
    ops["add_abs_16M_f32"] = (big, lambda x: jnp.abs(x + 1.0),
                              {"bytes": 2 * big.nbytes})
    ops["multiply_abs_16M_f32"] = (
        big, lambda x: jnp.abs(x * 1.0000001) * -1.0,
        {"bytes": 2 * big.nbytes})
    ops["exp_16M_f32"] = (big * 1e-6, lambda x: jnp.exp(x) * 1e-6,
                          {"bytes": 2 * big.nbytes})
    ops["reduce_sum_16M_f32"] = (
        big, lambda x: jnp.abs(x + (jnp.sum(x) * 1e-20)),
        {"bytes": big.nbytes})
    ops["softmax_4096x4096_f32"] = (
        big, lambda x: jax.nn.softmax(x, axis=-1) + x * 1e-6,
        {"bytes": 4 * big.nbytes})
    ops["transpose_4096x4096_f32"] = (
        big, lambda x: jnp.abs(jnp.transpose(x)),
        {"bytes": 2 * big.nbytes})
    ln_w = _f32(rng, 1024)
    act = _bf16(rng, 4096, 1024)
    ops["layer_norm_4096x1024_bf16"] = (
        act,
        lambda x: ((x.astype(jnp.float32)
                    - jnp.mean(x.astype(jnp.float32), -1,
                               keepdims=True))
                   * jax.lax.rsqrt(
                       jnp.var(x.astype(jnp.float32), -1,
                               keepdims=True) + 1e-5)
                   * ln_w).astype(jnp.bfloat16),
        {"bytes": 2 * act.nbytes})

    # -- memory / indexing -------------------------------------------
    table = _f32(rng, 50304, 256)
    idx = np.random.RandomState(1).randint(0, 50304, (8192,))
    idx_j = jnp.asarray(idx, jnp.int32)
    def _gather(x):
        # indices derive from the carry so the take cannot hoist out
        # of the loop as a loop-invariant
        shift = jnp.int32(jnp.abs(x[0, 0]) * 1e-20)
        return jnp.take(table, idx_j + shift, axis=0) + x * 1e-6

    ops["gather_8192_of_50304x256"] = (
        _f32(rng, 8192, 256), _gather,
        {"bytes": 2 * 8192 * 256 * 4})
    ops["scatter_add_8192_into_50304x256"] = (
        table,
        lambda t: t.at[idx_j].add(jnp.float32(1e-7)),
        {"bytes": 2 * 8192 * 256 * 4})

    # -- fused attention ---------------------------------------------
    try:
        from paddle_tpu.incubate.nn.attention_pallas import (
            flash_attention)

        q = _bf16(rng, 4, 16, 1024, 64)
        kv = _bf16(rng, 4, 16, 1024, 64)

        def fa(x):
            o = flash_attention(x, kv, kv, True, 0.125)
            return (x + o * jnp.bfloat16(1e-6))

        ops["flash_attention_fwd_4x16x1024x64"] = (
            q, fa, {"flops": 2 * 2 * 4 * 16 * 1024 * 1024 * 64 // 2})
    except Exception:
        pass

    # -- fused layernorm->gelu vs the unfused XLA composition --------
    # (ISSUE 8 acceptance: the fused kernel must beat this twin on
    # TPU; on CPU both pallas entries record errors — the kernels are
    # TPU/interpret-only — and the gate skips unresolved entries)
    ln_w2 = _f32(rng, 1024)
    ln_b2 = _f32(rng, 1024)
    act2 = _bf16(rng, 4096, 1024)

    def _unfused_ln_gelu(x):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, -1, keepdims=True)
        var = jnp.var(xf, -1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + 1e-5) * ln_w2 + ln_b2
        return (jax.nn.gelu(y).astype(jnp.bfloat16)
                + x * jnp.bfloat16(1e-3))

    ops["layernorm_gelu_unfused_4096x1024_bf16"] = (
        act2, _unfused_ln_gelu, {"bytes": 2 * act2.nbytes})
    try:
        from paddle_tpu.incubate.nn.pallas.layernorm import (
            fused_layer_norm)

        def _fused_ln_gelu(x):
            y = fused_layer_norm(x, ln_w2, ln_b2, 1e-5, "gelu", True,
                                 False)
            return y + x * jnp.bfloat16(1e-3)

        ops["fused_layernorm_gelu_4096x1024_bf16"] = (
            act2, _fused_ln_gelu, {"bytes": 2 * act2.nbytes})
    except Exception:
        pass

    # -- fused multi-tensor adam update vs the plain composition -----
    G = 128  # 128 chunks x 32768 = 4.2M parameters
    p0 = _f32(rng, G, 256, 128)
    m0 = jnp.zeros((G, 256, 128), jnp.float32)
    v0 = jnp.zeros((G, 256, 128), jnp.float32)
    gk = _f32(rng, G, 256, 128) * jnp.float32(1e-2)
    d1c = jnp.full((G, 1), 0.1, jnp.float32)
    d2c = jnp.full((G, 1), 0.001, jnp.float32)

    def _unfused_adam(carry):
        p, m, v = carry
        m2 = 0.9 * m + 0.1 * gk
        v2 = 0.999 * v + 0.001 * gk * gk
        p2 = p - 1e-3 * (m2 / 0.1) / (jnp.sqrt(v2 / 0.001) + 1e-8)
        return (p2, m2, v2)

    ops["adam_update_unfused_4M"] = ((p0, m0, v0), _unfused_adam,
                                     {"bytes": 7 * p0.nbytes})
    try:
        from paddle_tpu.incubate.nn.pallas.optim import (
            fused_adam_chunks)
        wd0 = jnp.zeros((G, 1), jnp.float32)
        lr0 = jnp.float32(1e-3)

        def _fused_adam(carry):
            p, m, v = carry
            return fused_adam_chunks(p, gk, m, v, lr0, d1c, d2c, wd0,
                                     beta1=0.9, beta2=0.999, eps=1e-8)

        ops["fused_adam_update_4M"] = ((p0, m0, v0), _fused_adam,
                                       {"bytes": 7 * p0.nbytes})
    except Exception:
        pass
    return ops


def run_all(n=16):
    results = {}
    for name, (init, step, work) in build_ops().items():
        try:
            dt = _chain_time(step, init, n=n)
            if dt <= 2e-9:
                # the (T(2n)-T(n)) difference never cleared the timing
                # floor even at the max chain length: record the fact,
                # not a fake 0us/absurd-GBps number (review r5)
                results[name] = {"unresolved": True}
            else:
                rec = {"us": round(dt * 1e6, 2)}
                if "flops" in work:
                    rec["tflops"] = round(work["flops"] / dt / 1e12, 2)
                if "bytes" in work:
                    rec["gbps"] = round(work["bytes"] / dt / 1e9, 1)
                results[name] = rec
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: "
                                      f"{str(e)[:160]}"}
        print("[op]", name, json.dumps(results[name]), flush=True)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", action="store_true",
                    help="write the baseline record")
    ap.add_argument("--check", action="store_true",
                    help="gate against the committed baseline")
    ap.add_argument("--tol", type=float, default=0.25)
    args = ap.parse_args()

    import jax

    platform = jax.devices()[0].platform
    results = run_all()
    out = {"platform": platform, "ops": results}
    if args.save:
        # merge: an unresolved/errored/0-rounded new measurement must
        # not evict a previously RESOLVED baseline entry; deltas vs
        # the old baseline print at the gate's own tolerance so a
        # --save cannot silently ratchet past a real regression, and
        # an op whose value moved by more than the tolerance across
        # clean re-saves of IDENTICAL code is marked volatile — the
        # gate then skips it loudly (tunnel-noise samples: layer_norm
        # recorded 3/12/2014us across three clean runs).
        if os.path.exists(BASELINE_PATH):
            with open(BASELINE_PATH) as f:
                prev = json.load(f).get("ops", {})
            for name, rec in list(out["ops"].items()):
                old_rec = prev.get(name, {})
                if rec.get("us", 0) <= 0 and old_rec.get("us", 0) > 0:
                    out["ops"][name] = old_rec
                    print(f"KEEP {name}: new run unresolved; keeping "
                          f"baseline {old_rec['us']}us",
                          file=sys.stderr)
                elif (rec.get("us", 0) > 0 and old_rec.get("us", 0) > 0
                      and abs(rec["us"] - old_rec["us"])
                      > args.tol * old_rec["us"]):
                    rec["volatile"] = True
                    print(f"DELTA {name}: {old_rec['us']}us -> "
                          f"{rec['us']}us (>{args.tol:.0%} on identical"
                          " code — marked volatile; the gate will "
                          "skip it loudly)", file=sys.stderr)
                elif old_rec.get("volatile") and rec.get("us", 0) > 0:
                    rec["volatile"] = True  # sticky until curated
        os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
        with open(BASELINE_PATH, "w") as f:
            json.dump(out, f, indent=1)
        print(f"baseline written: {BASELINE_PATH}", file=sys.stderr)
    print(json.dumps(out))  # after the merge: stdout == written record
    if args.check:
        if not os.path.exists(BASELINE_PATH):
            print("no baseline to check against", file=sys.stderr)
            return 1
        with open(BASELINE_PATH) as f:
            base = json.load(f)
        if base.get("platform") != platform:
            print(f"baseline platform {base.get('platform')} != "
                  f"{platform}; skipping gate", file=sys.stderr)
            return 0
        bad = []
        for name, b in base["ops"].items():
            # iterate the BASELINE so a gated op that crashed or went
            # missing in the current run FAILS instead of vanishing
            rec = results.get(name)
            if b.get("us", 0) <= 0:
                print(f"SKIP {name}: no resolved baseline to gate "
                      "against", file=sys.stderr)
                continue
            if b.get("volatile"):
                print(f"SKIP {name}: baseline marked volatile "
                      "(tunnel-noise resolution — see --save DELTA)",
                      file=sys.stderr)
                continue
            if rec is None or "error" in rec:
                bad.append((name, b["us"],
                            rec.get("error", "missing from run")
                            if rec else "missing from run"))
            elif rec.get("us", 0) <= 0:
                bad.append((name, b["us"], "unresolved measurement"))
            elif rec["us"] > b["us"] * (1 + args.tol):
                bad.append((name, b["us"], f"{rec['us']}us"))
        for name, was, now in bad:
            print(f"REGRESSION {name}: {was}us -> {now}",
                  file=sys.stderr)
        return 1 if bad else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
