"""ResNet-50 config sweep (r5 perf round): bench.py methodology.

usage: python benchmarks/exp_resnet.py '{"name":"b128"}' \
           '{"name":"b256","batch":256}' ...
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def run_variant(spec):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.vision.models import resnet50

    spec = dict(spec)
    name = spec.pop("name")
    batch = spec.pop("batch", 128)
    steps = spec.pop("steps", 60)
    warmup = spec.pop("warmup", 5)
    windows = spec.pop("windows", 3)
    paddle.seed(0)
    net = resnet50()
    net = amp.decorate(net, level="O2", dtype="bfloat16")
    ce = nn.CrossEntropyLoss()
    opt = optim.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=net.parameters(),
                         multi_precision=True)
    step = TrainStepCompiler(net, opt, lambda o, y: ce(o, y))
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, 224, 224)
                         .astype(np.float32))
    x._value = x._value.astype(jnp.bfloat16)
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int64))
    t0 = time.perf_counter()
    for _ in range(warmup):
        loss = step(x, y)
    first = float(loss.item())
    compile_s = time.perf_counter() - t0
    dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        last = float(loss.item())
        dts.append((time.perf_counter() - t0) / steps)
    dt = float(np.median(dts))
    assert np.isfinite(last) and last < first, (name, first, last)
    mfu = 3 * 4.09e9 * batch / dt / 197e12
    rec = {"name": name, "imgs_s": round(batch / dt, 1),
           "ms_step": round(dt * 1e3, 2), "mfu": round(mfu, 4),
           "compile_s": round(compile_s, 1)}
    print("[res]", json.dumps(rec), flush=True)
    return rec


def main():
    for arg in sys.argv[1:]:
        spec = json.loads(arg)
        try:
            run_variant(spec)
        except Exception as e:
            print("[res]", json.dumps({"name": spec.get("name"),
                                       "error": f"{type(e).__name__}: "
                                                f"{str(e)[:300]}"}),
                  flush=True)


if __name__ == "__main__":
    main()
