"""Framework-level helpers: save/load, default dtype, in_dygraph_mode.

Parity target: python/paddle/framework/io.py (paddle.save/load:553,769),
python/paddle/framework/framework.py (set_default_dtype).

TPU-native: checkpoints are pickled nested dicts of numpy arrays —
device-agnostic and portable; tensors are materialized host-side at
save and re-placed on the current device at load. (The reference
pickles LoDTensor protocol buffers.)
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core import flags
from .core.dtype import convert_dtype, dtype_name
from .core.tensor import Tensor


def set_default_dtype(d):
    flags.set_flags({"default_dtype": dtype_name(convert_dtype(d))})


def get_default_dtype():
    return flags.get_flag("default_dtype")


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _SavedTensor(np.asarray(obj._value))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


class _SavedTensor:
    """Tag so load() can rehydrate Tensors (vs plain ndarrays)."""

    def __init__(self, array):
        self.array = array


def _from_saved(obj, return_numpy=False):
    if isinstance(obj, _SavedTensor):
        if return_numpy:
            return obj.array
        return Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saved(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save — state_dicts / nested containers of Tensors."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    """paddle.load."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saved(obj, return_numpy=return_numpy)


def in_dygraph_mode():
    from . import static

    return not static._static_mode()


_dygraph_tracer = lambda: None

from .core.lod import LoDTensor, create_lod_tensor  # noqa: E402
