"""Framework-level helpers: save/load, default dtype, in_dygraph_mode.

Parity target: python/paddle/framework/io.py (paddle.save/load:553,769),
python/paddle/framework/framework.py (set_default_dtype).

TPU-native: checkpoints are pickled nested dicts of numpy arrays —
device-agnostic and portable; tensors are materialized host-side at
save and re-placed on the current device at load. (The reference
pickles LoDTensor protocol buffers.)
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core import flags
from .core.dtype import convert_dtype, dtype_name
from .core.tensor import Tensor


def set_default_dtype(d):
    flags.set_flags({"default_dtype": dtype_name(convert_dtype(d))})


def get_default_dtype():
    return flags.get_flag("default_dtype")


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _SavedTensor(np.asarray(obj._value))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


class _SavedTensor:
    """Tag so load() can rehydrate Tensors (vs plain ndarrays)."""

    def __init__(self, array):
        self.array = array


def _from_saved(obj, return_numpy=False):
    if isinstance(obj, _SavedTensor):
        if return_numpy:
            return obj.array
        return Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saved(v, return_numpy) for v in obj)
    return obj


def _atomic_write(path, write_fn):
    """Crash-safe publish: `write_fn(f)` writes into a same-directory
    tmp file, which is fsync'd and then os.replace()d over `path` —
    a crash (or kill -9) mid-write leaves either the old complete
    file or the new complete one, never a torn one. Shared by
    paddle.save and the elastic checkpoint writer."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # never leave the partial tmp behind (it would look like a
        # stray checkpoint to directory scanners)
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def save(obj, path, protocol=4, **configs):
    """paddle.save — state_dicts / nested containers of Tensors.

    Atomic at EVERY call site (tmp + fsync + os.replace): the elastic
    restore path depends on this — a torn .pd would burn one snapshot
    of fallback depth for no reason."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    _atomic_write(path, lambda f: pickle.dump(
        _to_saveable(obj), f, protocol=protocol))


def load(path, return_numpy=False, **configs):
    """paddle.load."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saved(obj, return_numpy=return_numpy)


def in_dygraph_mode():
    from . import static

    return not static._static_mode()


_dygraph_tracer = lambda: None

from .core.lod import LoDTensor, create_lod_tensor  # noqa: E402
