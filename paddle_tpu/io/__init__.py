"""paddle.io — data pipeline (reference: python/paddle/fluid/reader.py:146
DataLoader, python/paddle/fluid/dataloader/*).

TPU-native design: multi-worker loading uses multiprocessing workers
feeding a prefetch queue of numpy batches (shared memory via pickled
arrays); device transfer is a single `device_put` per batch which PJRT
overlaps with compute (the analog of BufferedReader's double buffering,
operators/reader/buffered_reader.cc).
"""
from __future__ import annotations

import contextlib
import itertools
import math
import os
import queue
import threading
import time as _time

import numpy as np

from .. import profiler as _profiler
from ..core import monitor as _monitor
from ..core.tensor import Tensor, to_tensor
from ..monitor import flight as _flight

# single-process analog of worker._SKIPPED: a batch whose every
# sample failed under on_bad_sample="skip" — consumed, never yielded
# (the chaos `io_fetch` site lives in worker._fetch_samples, which
# both the mp worker loop and the in-process _fetch go through)
_SKIPPED_BATCH = object()

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "BatchSampler", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
    "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * l)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    total = sum(lengths)
    perm = np.random.permutation(total)
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray([float(w) for w in weights])
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Groups sampler indices into batches. Elastic-training hooks
    (incubate.checkpoint / Model.fit(resume=...)):

      * `seed` + shuffle=True makes the per-epoch shuffle
        DETERMINISTIC (RandomState(seed + epoch)) and auto-reshuffled
        each epoch — required for bit-identical resume; seed=None
        keeps the legacy global-RNG shuffle.
      * `state_dict()`/`set_state_dict()` expose an (epoch, consumed)
        cursor; restoring fast-forwards the next iteration past the
        already-consumed batches. Note: a prefetching pipeline FETCHES
        ahead of the train loop, so mid-epoch cursors read from the
        sampler overcount by the prefetch depth — Model.fit's
        checkpoint callback records its own consumed-step cursor and
        restores through set_state_dict, which is exact.
    """

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False, seed=None):
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self._consumed = 0  # batches served (or skipped) this epoch
        self._skip = 0      # fast-forward pending from set_state_dict
        # the seeded shuffle may only replace an INTERNAL
        # RandomSampler — an explicit sampler carries its own policy
        # (weighted, subset, ...) that a uniform permutation of
        # positions would silently discard
        self._auto_sampler = sampler is None
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def _index_order(self):
        if self.shuffle and self.seed is not None \
                and getattr(self, "_auto_sampler", True):
            rng = np.random.RandomState(
                (int(self.seed) + self._epoch) % (2 ** 32))
            return iter(rng.permutation(len(self.sampler)).tolist())
        return iter(self.sampler)

    def __iter__(self):
        skip, self._skip = self._skip, 0
        self._consumed = 0
        n_batch = 0
        batch = []
        for idx in self._index_order():
            batch.append(idx)
            if len(batch) == self.batch_size:
                n_batch += 1
                self._consumed = n_batch
                if n_batch > skip:
                    yield batch
                batch = []
        if batch and not self.drop_last:
            n_batch += 1
            self._consumed = n_batch
            if n_batch > skip:
                yield batch
        # a fully consumed epoch advances the (seeded) shuffle — an
        # abandoned iterator (break) leaves the epoch in place so a
        # re-iteration replays the same order
        self._epoch += 1
        self._consumed = 0

    def set_epoch(self, epoch):
        self._epoch = int(epoch)
        self._consumed = 0
        self._skip = 0

    @property
    def _resume_deterministic(self):
        """Does replaying an epoch yield the same index order? If
        not, a restored (epoch, consumed) cursor fast-forwards past a
        DIFFERENT permutation — resume still runs, but not
        bit-identically (Model.fit warns)."""
        if self._auto_sampler:
            return (not self.shuffle) or self.seed is not None
        return isinstance(self.sampler, SequenceSampler)

    def state_dict(self):
        return {"epoch": self._epoch, "consumed": self._consumed,
                "seed": self.seed}

    def set_state_dict(self, state):
        self._epoch = int(state.get("epoch", 0))
        self._consumed = int(state.get("consumed", 0))
        self._skip = self._consumed
        if state.get("seed") is not None:
            self.seed = state["seed"]

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across ranks (reference:
    dataloader/batch_sampler.py DistributedBatchSampler). On TPU the
    "rank" is the process index in multi-host SPMD."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self._consumed = 0
        self._skip = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank:self.total_size:self.nranks]
        skip, self._skip = self._skip, 0
        self._consumed = 0
        n_batch = 0
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                n_batch += 1
                self._consumed = n_batch
                if n_batch > skip:
                    yield batch
                batch = []
        if batch and not self.drop_last:
            n_batch += 1
            self._consumed = n_batch
            if n_batch > skip:
                yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
        self._consumed = 0
        self._skip = 0

    # -- elastic resume (epoch is the shuffle seed here: set_epoch
    # discipline, reference batch_sampler.py) -------------------------
    @property
    def _resume_deterministic(self):
        return True  # the epoch number IS the shuffle seed

    def state_dict(self):
        return {"epoch": self.epoch, "consumed": self._consumed}

    def set_state_dict(self, state):
        self.epoch = int(state.get("epoch", 0))
        self._consumed = int(state.get("consumed", 0))
        self._skip = self._consumed


def get_worker_info():
    """In a worker process, describes this worker (reference
    dataloader/worker.py WorkerInfo); None in the main process."""
    from .worker import get_worker_info as _gwi

    return _gwi()


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return to_tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _np_collate(batch):
    """Collate into numpy (worker side — keeps device transfer in the
    main process)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate(list(s)) for s in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    return batch


def _detach_views(obj):
    """Copy numpy arrays that don't own their data (shm-slot views) so
    the caller owns the batch outright. Exact tuple/list/dict recurse
    cheaply; any other container (namedtuple, dataclass, subclass)
    deep-copies — deepcopy preserves the type AND detaches every array
    view, matching the ownership the old pickle round-trip gave."""
    if isinstance(obj, np.ndarray):
        return obj.copy() if obj.base is not None else obj
    if type(obj) is tuple:
        return tuple(_detach_views(o) for o in obj)
    if type(obj) is list:
        return [_detach_views(o) for o in obj]
    if type(obj) is dict:
        return {k: _detach_views(v) for k, v in obj.items()}
    if isinstance(obj, (int, float, complex, str, bytes, bool,
                        type(None))):
        return obj
    import copy

    return copy.deepcopy(obj)


def _auto_num_workers():
    """Worker count for num_workers=-1/"auto": PADDLE_IO_WORKERS when
    set, else os.cpu_count() capped at 16 (beyond that the trainer-side
    ring pops and the single H2D stream are the bottleneck, and each
    worker pins a whole shm ring). The trainer thread doesn't get a
    reserved core — it mostly blocks in ring pops, so decode workers
    on every core win even on 2-core hosts (measured: the pop-side
    memcpy overlaps worker decode)."""
    env = os.environ.get("PADDLE_IO_WORKERS")
    if env:
        try:
            # clamped to >= 1: auto-sizing always means SOME worker
            # pool (bench feeds this straight into MultiprocessLoader,
            # whose round-robin math divides by it); to disable
            # workers pass num_workers=0 explicitly
            return max(1, int(env))
        except ValueError:
            pass
    n = os.cpu_count() or 1
    return max(1, min(n, 16))


def _resolve_num_workers(n):
    if n in (-1, "auto"):
        return _auto_num_workers()
    return n


_cpu_backend = None


def _is_cpu_backend():
    global _cpu_backend
    if _cpu_backend is None:
        import jax

        _cpu_backend = jax.default_backend() == "cpu"
    return _cpu_backend


def _own_for_cpu(arr):
    """jax's CPU client zero-copies 64B-aligned numpy arrays into
    device buffers — a shm-ring slot view would then alias the ring
    past slot reuse/munmap (verified: mutating the backing buffer
    changes the "device" array). Detach views on the CPU backend; an
    accelerator device_put always copies off-host."""
    if arr.base is not None and _is_cpu_backend():
        return arr.copy()
    return arr


def _to_device(obj):
    if isinstance(obj, np.ndarray):
        return to_tensor(_own_for_cpu(obj))
    if isinstance(obj, tuple):
        return tuple(_to_device(o) for o in obj)
    if isinstance(obj, list):
        return [_to_device(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_device(v) for k, v in obj.items()}
    return obj


def _batch_mesh_sharding(ndim):
    """Sharding-aware prefetch placement: with a live single-process
    mesh whose 'dp' axis is real, batches land pre-sharded over dp on
    the leading dim — the DistributedTrainStepCompiler's device_put
    onto the same sharding is then a no-op instead of a re-layout.
    None (default placement) everywhere else; multi-process meshes
    need the compiler's hostify path, so they are left alone."""
    import jax

    try:
        from ..distributed import mesh as mesh_mod

        mesh = mesh_mod.get_mesh()
        if (mesh is None or jax.process_count() > 1 or ndim < 1
                or mesh.shape.get("dp", 1) <= 1):
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(
            mesh, PartitionSpec(*(("dp",) + (None,) * (ndim - 1))))
    except Exception:
        return None


def _device_put_batch(obj):
    """Prefetch-stage device placement: non-blocking, sharding-aware
    device_puts (PJRT overlaps the H2D copy with whatever the main
    thread is computing). Mirrors _to_device's output contract —
    ndarray leaves become device-backed Tensors, including Tensor's
    float64 -> default-float cast (toggling prefetch must never
    change batch dtypes)."""
    import jax

    if isinstance(obj, np.ndarray):
        arr = _own_for_cpu(obj)
        if arr.dtype == np.float64:
            from ..core import dtype as _dtype_mod

            arr = arr.astype(_dtype_mod.default_float_dtype())
        sh = _batch_mesh_sharding(arr.ndim)
        if sh is not None:
            try:
                v = jax.device_put(arr, sh)
            except Exception:
                v = jax.device_put(arr)  # e.g. dp doesn't divide batch
        else:
            v = jax.device_put(arr)
        return Tensor(v, stop_gradient=True, _internal=True)
    if isinstance(obj, tuple):
        return tuple(_device_put_batch(o) for o in obj)
    if isinstance(obj, list):
        return [_device_put_batch(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _device_put_batch(v) for k, v in obj.items()}
    return obj


def _host_nbytes(obj):
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if isinstance(obj, (tuple, list)):
        return sum(_host_nbytes(o) for o in obj)
    if isinstance(obj, dict):
        return sum(_host_nbytes(v) for v in obj.values())
    return 0


class DataLoader:
    """reference: fluid/reader.py:146 + dataloader_iter.py:326.

    num_workers>0, use_shared_memory=True (default): REAL multiprocess
    workers — forked processes compute/collate batches and hand them to
    the trainer through C shared-memory SPSC rings
    (utils/cpp/shm_ring.cc, the mmap_allocator.cc analog); supports
    worker_init_fn, timeout, and persistent_workers. With
    use_shared_memory=False, a thread prefetcher is used instead
    (enough when transforms are numpy-light).

    prefetch_to_device=N adds a device-feed stage: a background thread
    issues non-blocking, sharding-aware device_puts into a bounded
    N-deep buffer, so each batch's H2D transfer rides under the
    previous step's compute instead of blocking the training thread
    (the BufferedReader double-buffer, moved to the PJRT boundary).
    Default: on (depth 2) when a non-CPU backend is present, off on
    CPU; PADDLE_IO_DEVICE_PREFETCH=N overrides (0 disables, N>0
    forces depth N on any backend). Only default-collate batches are
    device-placed — a custom collate_fn keeps its raw batches, buffered
    but untouched. Observable via io/h2d_us and
    io/device_prefetch/{depth,stalls,bytes} counters."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, prefetch_to_device=None,
                 on_bad_sample=None, worker_restarts=None):
        """on_bad_sample: per-sample error policy — "raise" (default)
        fails the epoch on the first bad record; "skip" drops the
        sample, counts it under io/bad_samples, and keeps the epoch
        alive (a fully-failed batch is dropped whole). Map-style
        datasets only: an IterableDataset has no per-sample fetch to
        retry around (a raise mid-iterator invalidates the stream),
        so iterable pipelines keep fail-fast and "skip" warns.
        Default from PADDLE_IO_ON_BAD_SAMPLE.

        worker_restarts: how many times EACH mp worker may be
        restarted after dying or wedging (fresh shm ring, outstanding
        batches re-fed in order) before the epoch fails. Default
        PADDLE_IO_WORKER_RESTARTS (2). A worker that is alive but
        silent past PADDLE_IO_WORKER_TIMEOUT_S seconds counts as
        wedged (0 = never, the default).

        num_workers=-1 (or "auto") sizes the mp worker pool from the
        host: PADDLE_IO_WORKERS when set, else os.cpu_count() capped
        at 16 — an image pipeline saturates a multi-core host without
        per-machine tuning."""
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn
        self.num_workers = _resolve_num_workers(num_workers)
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self.prefetch_to_device = prefetch_to_device
        if on_bad_sample not in (None, "raise", "skip"):
            raise ValueError(
                f"DataLoader: on_bad_sample={on_bad_sample!r} "
                "(expected 'raise' or 'skip')")
        self.on_bad_sample = on_bad_sample
        self.worker_restarts = worker_restarts
        self._pf_orphans = []  # feeder threads outliving their epoch
        self._mp_loader = None
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode and self._bad_sample_policy() == "skip":
            import warnings

            warnings.warn(
                "DataLoader: on_bad_sample='skip' has no effect on an "
                "IterableDataset (no per-sample fetch to retry "
                "around) — errors still fail the epoch",
                RuntimeWarning)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        elif not self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last) if batch_size is not None else None
        else:
            self.batch_size = batch_size
            self.batch_sampler = None
        self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # -- elastic resume ----------------------------------------------
    def state_dict(self):
        """Resumable-position cursor (delegates to the batch sampler's
        (epoch, consumed) state). IterableDataset pipelines have no
        replayable cursor and raise."""
        bs = self.batch_sampler
        if bs is None or not hasattr(bs, "state_dict"):
            raise TypeError(
                "DataLoader.state_dict() needs a batch_sampler with "
                "state (IterableDataset pipelines are not resumable)")
        return {"batch_sampler": bs.state_dict()}

    def set_state_dict(self, state):
        """Restore the cursor: the next __iter__ replays the saved
        epoch's (seeded) order and fast-forwards past the consumed
        batches."""
        bs = self.batch_sampler
        if bs is None or not hasattr(bs, "set_state_dict"):
            raise TypeError(
                "DataLoader.set_state_dict() needs a batch_sampler "
                "with state")
        bs.set_state_dict(state.get("batch_sampler", state))

    # -- fault policy (shared by the mp and in-process pipelines) ----
    def _bad_sample_policy(self):
        v = self.on_bad_sample
        if v is None:
            v = os.environ.get("PADDLE_IO_ON_BAD_SAMPLE") or "raise"
            if str(v).lower() not in ("raise", "skip"):
                # the ctor kwarg validates loudly — the env leg must
                # not silently turn a typo ('drop', 'sip') into
                # fail-fast, which is the exact incident the knob
                # exists to prevent (warnings dedup per call site)
                import warnings

                warnings.warn(
                    f"PADDLE_IO_ON_BAD_SAMPLE={v!r} is not "
                    "'raise'|'skip' — falling back to 'raise'",
                    RuntimeWarning)
        return "skip" if str(v).lower() == "skip" else "raise"

    def _worker_restart_budget(self):
        n = self.worker_restarts
        if n is None:
            n = _flight._env_int("PADDLE_IO_WORKER_RESTARTS", 2)
        return max(0, int(n))

    def _fetch(self, indices, to_device=True, telemetry=True,
               policy=None):
        # io telemetry: this runs on the CALLING thread — under the
        # threaded prefetcher that is the producer thread, whose spans
        # the process-wide recorder now captures (the thread-local
        # recorder used to drop them). telemetry=False (the
        # batch_size=None per-SAMPLE path) keeps the bad-sample
        # policy + chaos site but skips the span/counters/flight
        # event — a million-sample pass would otherwise flood the
        # bounded flight ring and evict the step/collective evidence
        # dump bundles exist to keep
        from .worker import _fetch_samples, note_bad_samples

        with _profiler.RecordEvent("io/fetch_batch", "Dataloader") \
                if telemetry else contextlib.nullcontext():
            t0 = _time.perf_counter()
            samples, skipped, err = _fetch_samples(
                self.dataset, indices, None,
                policy or self._bad_sample_policy())
            if skipped:
                note_bad_samples(skipped, err)
                if not samples:
                    return _SKIPPED_BATCH
            collate = self.collate_fn or _np_collate
            batch = collate(samples)
            if self.collate_fn is None and to_device:
                batch = _to_device(batch)
        if telemetry:
            us = int((_time.perf_counter() - t0) * 1e6)
            _monitor.stat_add("io/batches", 1)
            _monitor.stat_add("io/fetch_us", us)
            # the fetch DISTRIBUTION (ISSUE 15): a p99 fetch stall
            # hides inside the cumulative io/fetch_us counter
            _monitor.hist_observe("io/hist/fetch_us", us)
            _flight.record("io_fetch", n=len(indices), us=us)
        return batch

    def _iter_batches(self, to_device=True):
        # to_device=False yields HOST batches — the device-prefetch
        # stage owns placement then (it must see numpy to issue the
        # sharding-aware device_put itself)
        dev = _to_device if to_device else (lambda b: b)
        if self._iterable_mode:
            it = iter(self.dataset)
            collate = self.collate_fn or _np_collate
            if self.batch_size is None:
                for sample in it:
                    yield dev(sample)
                return
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                out = collate(batch)
                yield out if self.collate_fn is not None else dev(out)
        elif self.batch_sampler is None:
            # one sample per index. Default-collate samples route
            # through _fetch so the bad-sample policy and the chaos
            # io_fetch site apply like every other path; a custom
            # collate_fn keeps this path's legacy contract exactly
            # (_np_collate + device placement, collate_fn unused)
            pol = self._bad_sample_policy()  # once, not per sample
            for i in range(len(self.dataset)):
                if self.collate_fn is not None:
                    yield dev(_np_collate([self.dataset[i]]))
                    continue
                batch = self._fetch([i], to_device=to_device,
                                    telemetry=False, policy=pol)
                if batch is _SKIPPED_BATCH:
                    continue
                yield batch
        else:
            pol = self._bad_sample_policy()  # once, not per batch
            for indices in self.batch_sampler:
                batch = self._fetch(indices, to_device=to_device,
                                    policy=pol)
                if batch is _SKIPPED_BATCH:
                    continue  # every sample failed under "skip"
                yield batch

    def _multiprocess_iter(self, to_device=True):
        from .worker import MultiprocessLoader

        def make_loader():
            slot_mb = int(__import__("os").environ.get(
                "FLAGS_dataloader_shm_slot_mb", "64"))
            return MultiprocessLoader(
                self.dataset, self.collate_fn or _np_collate,
                self.num_workers, max(2, self.prefetch_factor),
                slot_mb, self.worker_init_fn, self.timeout,
                self.persistent_workers,
                iterable_mode=self._iterable_mode,
                batch_size=self.batch_size,
                drop_last=self.drop_last,
                default_collate=self.collate_fn is None,
                on_bad_sample=self._bad_sample_policy(),
                restarts=self._worker_restart_budget(),
                wedge_timeout_s=_flight._env_float(
                    "PADDLE_IO_WORKER_TIMEOUT_S", 0.0))

        try:
            if self.persistent_workers:
                # one long-lived worker pool; run_epoch serializes
                # epochs (a second concurrent iterator raises). A pool
                # torn down by a worker error/timeout is rebuilt.
                if self._mp_loader is None or not self._mp_loader.procs:
                    self._mp_loader = make_loader()
                loader, owned = self._mp_loader, False
            else:
                # each iterator owns an independent pool — concurrent
                # iterators (zip(dl, dl)) cannot corrupt each other
                loader, owned = make_loader(), True
        except (RuntimeError, OSError, FileNotFoundError) as e:
            # shared-memory transport unavailable (no g++ / read-only
            # cache dir): fall back to the threaded prefetcher
            import warnings

            warnings.warn(
                f"multiprocess DataLoader unavailable ({e}); falling "
                "back to thread prefetching — pass "
                "use_shared_memory=False to silence", RuntimeWarning)
            yield from self._threaded_iter(to_device=to_device)
            return

        if self.batch_sampler is not None:
            batches = iter(self.batch_sampler)
        elif not self._iterable_mode:
            # batch_size=None: one sample per index (matches the
            # single-process path)
            batches = ([i] for i in range(len(self.dataset)))
        else:
            batches = []
        from .worker import _zero_copy_enabled

        raw = self.collate_fn is not None
        # detach only when zero-copy transport is on: plain-pickle
        # batches already own immutable bytes-backed data, and copying
        # them would add a gratuitous full-batch memcpy (review)
        detach_host = _zero_copy_enabled()
        detach = raw and detach_host
        try:
            gen = loader.run_epoch(batches)
            while True:
                # span the blocking ring pop: time the trainer spends
                # here is the input pipeline failing to keep up
                with _profiler.RecordEvent("io/shm_pop", "Dataloader"):
                    try:
                        batch = next(gen)
                    except StopIteration:
                        break
                _monitor.stat_add("io/batches", 1)
                _flight.record("io_fetch", transport="shm")
                # zero-copy batches alias the shm ring slot, valid only
                # until that worker's next batch is fetched. The
                # default path's _to_device copies host->device before
                # the user sees the batch; raw mode (custom collate_fn)
                # hands out numpy arrays, so detach slot-aliasing ones
                # with one memcpy (still 3 copies cheaper than the old
                # pickle+ring+unpickle transport).
                if raw:
                    yield _detach_views(batch) if detach else batch
                elif to_device:
                    yield _to_device(batch)
                elif detach_host:
                    # device placement deferred to the prefetch thread,
                    # which runs AFTER the next ring pop may have
                    # recycled this slot — hand it an owned copy
                    yield _detach_views(batch)
                else:
                    # zero-copy transport off: plain-pickle batches
                    # already own their bytes — copying would add a
                    # gratuitous full-batch memcpy
                    yield batch
        finally:
            if owned:
                loader.shutdown()

    def _threaded_iter(self, to_device=True):
        # threaded prefetch: producer thread pulls batches, main
        # thread does device_put
        q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            try:
                for b in self._iter_batches(to_device=to_device):
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item

    def _device_prefetch_depth(self):
        """Resolved depth of the device-feed stage (0 = off).
        Precedence: constructor arg > PADDLE_IO_DEVICE_PREFETCH env >
        auto (2 on non-CPU backends, 0 on CPU)."""
        n = self.prefetch_to_device
        if n is None:
            env = os.environ.get("PADDLE_IO_DEVICE_PREFETCH")
            if env:
                try:
                    n = int(env)
                except ValueError:
                    n = None
        if n is None:
            try:
                n = 0 if _is_cpu_backend() else 2
            except Exception:
                n = 0
        return max(0, int(n))

    # bound (seconds) on waiting for the feeder thread when the
    # consumer abandons a prefetching iterator; a feeder mid-fetch
    # that outlives it is parked in _pf_orphans and reaped before the
    # next epoch starts (persistent worker pools can't serve two
    # epochs at once)
    _PF_REAP_S = 2.0

    def _reap_orphan_feeders(self):
        """Join feeder threads abandoned by earlier epochs. Only a
        PERSISTENT shm worker pool makes the wait semantically
        required: its run_epoch busy-flag is held until the orphan's
        in-flight fetch completes and its drain runs, and starting the
        next epoch before that raises 'already serving an iterator'.
        Everything else (thread/single-process pipelines, owned pools)
        has no exclusivity at stake — just prune finished daemons
        without blocking the training thread."""
        if not self._pf_orphans:
            return
        must_wait = (self.persistent_workers and self.num_workers > 0
                     and self.use_shared_memory)
        deadline = _time.monotonic() + (30.0 if must_wait else 0.0)
        alive = []
        for t in self._pf_orphans:
            t.join(timeout=max(0.0, deadline - _time.monotonic()))
            if t.is_alive():
                alive.append(t)
        self._pf_orphans = alive

    def _device_prefetch_iter(self, depth):
        """Device-feed stage: a background thread pulls HOST batches
        from the underlying pipeline, issues the (non-blocking,
        sharding-aware) device_put, and parks the device-resident
        batch in a bounded buffer — H2D for batch i+1..i+depth rides
        under the consumer's compute on batch i. Batch order is the
        single FIFO queue's order (never reordered, never dropped);
        abandoning the iterator mid-epoch (break/GC) stops the feeder
        thread and closes the inner pipeline."""
        if self.num_workers > 0 and self.use_shared_memory:
            inner = self._multiprocess_iter(to_device=False)
        else:
            # the feed thread already backgrounds the fetch; a second
            # producer thread (_threaded_iter) would buy nothing
            inner = self._iter_batches(to_device=False)
        place = (_device_put_batch if self.collate_fn is None
                 else (lambda b: b))
        q = queue.Queue(maxsize=max(1, depth))
        stop = threading.Event()
        sentinel = object()
        failure = []

        def feeder():
            try:
                for b in inner:
                    nb = _host_nbytes(b)
                    t0 = _time.perf_counter()
                    d = place(b)
                    us = int((_time.perf_counter() - t0) * 1e6)
                    _monitor.stat_add("io/h2d_us", us)
                    _monitor.stat_add("io/device_prefetch/bytes", nb)
                    _flight.record("io_h2d", us=us, bytes=nb)
                    while not stop.is_set():
                        try:
                            q.put(d, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surfaced on the consumer side
                failure.append(e)
            finally:
                try:
                    inner.close()
                except Exception:
                    pass
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=feeder, daemon=True,
                             name="paddle-io-device-feed")
        _flight.record("io_device_prefetch", phase="start", depth=depth)
        t.start()
        try:
            first = True
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    # the consumer outran the feeder — each stall is a
                    # step that WAITED on input (the signal the depth
                    # knob is tuned against). The first get of an
                    # epoch always finds an empty queue (the feeder
                    # just started) — counting it would give the
                    # signal a floor of one stall per epoch that no
                    # depth could tune away.
                    if not first:
                        _monitor.stat_add("io/device_prefetch/stalls",
                                          1)
                    item = q.get()
                first = False
                _monitor.stat_set("io/device_prefetch/depth", q.qsize())
                if item is sentinel:
                    if failure:
                        raise failure[0]
                    return
                yield item
        finally:
            stop.set()
            # unblock a feeder parked on q.put, then reap it — with a
            # BOUND: a feeder mid-fetch (slow __getitem__, blocked
            # stream) can't observe stop until its item completes, and
            # abandoning an iterator must not hang the main thread on
            # it (the daemon thread exits at its next stop check). A
            # survivor is parked for _reap_orphan_feeders: the next
            # epoch must wait for it before reusing persistent pools.
            deadline = _time.monotonic() + self._PF_REAP_S
            while t.is_alive() and _time.monotonic() < deadline:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)
            if t.is_alive():
                self._pf_orphans.append(t)
            _flight.record("io_device_prefetch", phase="stop",
                           reaped=not t.is_alive())

    def __iter__(self):
        self._reap_orphan_feeders()
        depth = self._device_prefetch_depth()
        if depth > 0:
            yield from self._device_prefetch_iter(depth)
            return
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        if self.use_shared_memory:
            yield from self._multiprocess_iter()
            return
        yield from self._threaded_iter()
