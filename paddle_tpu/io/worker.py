"""Multiprocess DataLoader workers with shared-memory transport.

Parity target: python/paddle/fluid/dataloader/dataloader_iter.py:326
(_DataLoaderIterMultiProcess), worker.py (worker loop + WorkerInfo),
and the mmap shared-memory tensor path
(paddle/fluid/memory/allocation/mmap_allocator.cc).

TPU-native design: each worker OWNS one C shared-memory SPSC ring
(utils/cpp/shm_ring.cc — lock-free head/tail atomics); batches are
pickled (protocol 5) straight into the ring slot, so worker->trainer
transport never touches a pipe. Batch i is assigned to worker i % W
and the trainer pops rings in that order — global batch order is
deterministic regardless of worker speed (the reference's reorder
buffer, by construction). The trainer thread then hands bytes to PJRT
host->device transfer.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import sys as _sys
import threading

import numpy as np

from ..monitor import chaos as _chaos
from ..monitor import sanitize as _sanitize

_EOF = b"\x00PDEOF"
_ERR = b"\x00PDERR"
# skip marker (on_bad_sample="skip"): pickle of (partial batch or
# None, n_skipped, formatted traceback of the last failure) — the
# trainer counts io/bad_samples and drops a fully-failed batch
_SKP = b"\x00PDSKP"

# run_epoch-internal sentinel: a fed index batch whose every sample
# failed under the skip policy — consumed (the fed/popped accounting
# must advance) but never yielded
_SKIPPED = object()

_bad_sample_logged = [False]


def note_bad_samples(n, err, worker=None):
    """Trainer-side accounting for skipped samples: counter + flight
    event always, and the FIRST failure's traceback once at VLOG(0) —
    on_bad_sample='skip' must not force an operator to rerun in
    'raise' mode just to learn WHY records are failing."""
    from ..core import monitor as _monitor
    from ..monitor import flight as _flight

    _monitor.stat_add("io/bad_samples", n)
    _flight.record("io_bad_sample", n=n, worker=worker)
    if err and not _bad_sample_logged[0]:
        _bad_sample_logged[0] = True
        try:
            _monitor.VLOG(
                0, "DataLoader on_bad_sample='skip' dropped a sample "
                   "(io/bad_samples counts them); first failure:\n"
                   + str(err))
        except Exception:
            pass

# zero-copy frame: magic(8) meta_len(8) nbufs(8) [off(8) len(8)]*n
# meta-pickle then 64B-aligned out-of-band buffers. Arrays deserialize
# ALIASING the shm slot — the slot is held until the next pop on the
# same ring, and the trainer's _to_device does the single remaining
# copy (host->device).
_ZC_MAGIC = b"PDZC\x01\x00\x00\x00"


def _zero_copy_enabled():
    return os.environ.get("FLAGS_dataloader_zero_copy", "1") != "0"


def _overlap_decode_enabled():
    """Worker-side decode/collate overlap (PADDLE_IO_OVERLAP_DECODE,
    default on): a decode thread runs dataset[i] fetches one index
    batch AHEAD while the worker main thread collates into / waits on
    the shm ring — sample decode rides under ring backpressure instead
    of serializing behind it."""
    return os.environ.get("PADDLE_IO_OVERLAP_DECODE", "1") not in (
        "0", "false", "off")


def _slot_overflow(nbytes, slot_bytes):
    return ValueError(
        f"batch of {nbytes} bytes exceeds the shared-memory slot "
        f"({slot_bytes}B) — raise FLAGS_dataloader_shm_slot_mb or "
        "shrink the batch")


def _push_batch(ring, batch):
    """Serialize a batch into `ring`. Zero-copy framing when enabled:
    pickle protocol-5 splits numpy array bodies out as buffers, and
    both the metadata and the buffers are written DIRECTLY into the
    reserved shm slot (no intermediate bytes object, no second copy in
    ring_push)."""
    import struct

    if not _zero_copy_enabled():
        ring.push(pickle.dumps(batch, protocol=5))
        return
    bufs = []
    meta = pickle.dumps(batch, protocol=5, buffer_callback=bufs.append)
    raws = [b.raw().cast("B") for b in bufs]
    n = len(raws)
    header = 24 + n * 16
    off = header + len(meta)
    table = []
    for r in raws:
        off = (off + 63) & ~63          # 64B-align each array body
        table.append((off, r.nbytes))
        off += r.nbytes
    total = off
    if total > ring.slot_bytes:
        raise _slot_overflow(total, ring.slot_bytes)
    mv = ring.reserve()
    struct.pack_into("<8sQQ", mv, 0, _ZC_MAGIC, len(meta), n)
    for i, (o, ln) in enumerate(table):
        struct.pack_into("<QQ", mv, 24 + i * 16, o, ln)
    mv[header:header + len(meta)] = meta
    for (o, ln), r in zip(table, raws):
        mv[o:o + ln] = r
    mv.release()
    ring.commit(total)


# the stacked fast path writes array bodies at fixed offsets after a
# reserved header page, so collation happens DIRECTLY into the slot
# (one copy per sample total: sample -> shm; the separate np.stack
# batch materialization disappears)
_ZC_HEADER_BYTES = 4096


def _try_push_stacked(ring, samples):
    """Collate-into-slot fast path for the default collate: samples
    that are flat tuples/lists of array-likes with identical structure
    stack straight into the reserved shm slot. Returns False when the
    structure is unsupported (caller falls back to collate+push)."""
    import struct

    first = samples[0]
    if not isinstance(first, (tuple, list)):
        return False
    k = len(first)
    try:
        arrs0 = [np.asarray(f) for f in first]
    except Exception:
        return False
    if any(a.dtype == object for a in arrs0):
        return False
    B = len(samples)
    off = _ZC_HEADER_BYTES
    layout = []
    for a in arrs0:
        off = (off + 63) & ~63
        nbytes = int(a.nbytes) * B
        layout.append((off, (B,) + a.shape, a.dtype))
        off += nbytes
    total = off
    if total > ring.slot_bytes:
        raise _slot_overflow(total, ring.slot_bytes)
    mv = ring.reserve()
    views = batch = bufs = None
    try:
        views = []
        for o, shape, dtype in layout:
            v = np.frombuffer(mv, dtype=dtype,
                              count=int(np.prod(shape)),
                              offset=o).reshape(shape)
            views.append(v)
        for j, s in enumerate(samples):
            if len(s) != k:
                return False
            for i in range(k):
                src = np.asarray(s[i])
                if src.shape != layout[i][1][1:]:
                    # np.stack would raise on ragged samples — don't
                    # silently broadcast a wrong-shaped one (review);
                    # the generic fallback surfaces the real error
                    return False
                if src.dtype != layout[i][2]:
                    # np.stack would PROMOTE mixed dtypes (f32+f64 ->
                    # f64); copyto(casting="same_kind") into the
                    # sample-0 layout would instead silently DOWNCAST
                    # this sample — fall back to the generic
                    # collate+push path, which promotes like np.stack
                    return False
                # [j, ...] keeps a 0-d ndarray view for scalar fields
                # (plain [j] yields a numpy scalar copyto rejects)
                np.copyto(views[i][j, ...], src, casting="same_kind")
        # meta: pickle the slot-aliasing arrays out-of-band — the
        # buffer table then points at the bodies already in the slot
        bufs = []
        batch = tuple(views)
        meta = pickle.dumps(batch, protocol=5,
                            buffer_callback=bufs.append)
        n = len(bufs)
        header = 24 + n * 16
        if header + len(meta) > _ZC_HEADER_BYTES or n != k:
            return False  # generic path re-reserves the same slot
        struct.pack_into("<8sQQ", mv, 0, _ZC_MAGIC, len(meta), n)
        for i, (o, shape, dtype) in enumerate(layout):
            nb = int(np.prod(shape)) * np.dtype(dtype).itemsize
            struct.pack_into("<QQ", mv, 24 + i * 16, o, nb)
        mv[header:header + len(meta)] = meta
    except (TypeError, ValueError):
        return False  # dtype/casting surprise: let collate+push handle
    finally:
        # drop every slot-aliasing export (arrays, PickleBuffers)
        # before releasing the memoryview — release() raises
        # BufferError while exports are alive
        views = batch = bufs = None
        mv.release()
    ring.commit(total)
    return True


def _decode_view(view):
    """Deserialize a zero-copy framed batch from a slot view, or None
    if the payload is not zero-copy framed (markers, plain pickles).
    The returned object's arrays alias `view`'s memory."""
    import struct

    if len(view) < 24 or bytes(view[:8]) != _ZC_MAGIC:
        return None
    _, meta_len, n = struct.unpack_from("<8sQQ", view, 0)
    header = 24 + n * 16
    table = [struct.unpack_from("<QQ", view, 24 + i * 16)
             for i in range(n)]
    meta = view[header:header + meta_len]
    bufs = [view[o:o + ln] for (o, ln) in table]
    return pickle.loads(meta, buffers=bufs)

_lib = None
_lib_lock = _sanitize.lock("io.shm_lib")


def _ring_lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            from ..utils.cpp_extension import load

            src = os.path.join(os.path.dirname(__file__), "..", "utils",
                               "cpp", "shm_ring.cc")
            lib = load("shm_ring", [os.path.abspath(src)],
                       extra_ldflags=["-lrt"])
            lib.ring_open.restype = ctypes.c_void_p
            lib.ring_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64, ctypes.c_int]
            lib.ring_push.restype = ctypes.c_int
            lib.ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int64]
            lib.ring_pop.restype = ctypes.c_int64
            lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64, ctypes.c_int64]
            lib.ring_push_reserve.restype = ctypes.c_void_p
            lib.ring_push_reserve.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
            lib.ring_push_commit.restype = ctypes.c_int
            lib.ring_push_commit.argtypes = [ctypes.c_void_p,
                                             ctypes.c_uint64]
            lib.ring_pop_view.restype = ctypes.c_void_p
            lib.ring_pop_view.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_uint64),
                                          ctypes.c_int64]
            lib.ring_pop_release.argtypes = [ctypes.c_void_p]
            lib.ring_close.argtypes = [ctypes.c_void_p]
            lib.ring_unlink.argtypes = [ctypes.c_char_p]
            _lib = lib
        return _lib


class ShmRing:
    """One SPSC ring in POSIX shared memory (ctypes over shm_ring.cc)."""

    def __init__(self, name, slots, slot_bytes, create):
        self._lib = _ring_lib()
        self.name = name.encode()
        self.slot_bytes = slot_bytes
        self._h = self._lib.ring_open(self.name, slots, slot_bytes,
                                      1 if create else 0)
        if not self._h:
            raise OSError(f"shm ring {name} open failed")
        self._creator = create
        self._pending = False
        # bind ctypes helpers: module globals are None'd during
        # interpreter shutdown while generator finalizers may still
        # drain rings
        self._c_uint64 = ctypes.c_uint64
        self._byref = ctypes.byref
        self._c_ubyte = ctypes.c_ubyte

    def push(self, data: bytes, timeout_ms=-1):
        rc = self._lib.ring_push(self._h, data, len(data), timeout_ms)
        if rc == -2:
            raise _slot_overflow(len(data), self.slot_bytes)
        return rc == 0

    # -- zero-copy API (r5): batches serialize straight into the slot
    # and deserialize straight out of it; see _push_batch/_decode_view
    def reserve(self, timeout_ms=-1):
        """Writable memoryview over the next free slot's payload area
        (full slot_bytes), or None on timeout. Publish with commit()."""
        if not self._h:
            return None
        ptr = self._lib.ring_push_reserve(self._h, timeout_ms)
        if not ptr:
            return None
        arr = (self._c_ubyte * self.slot_bytes).from_address(ptr)
        return memoryview(arr).cast("B")

    def commit(self, length):
        rc = self._lib.ring_push_commit(self._h, length)
        if rc == -2:
            raise _slot_overflow(length, self.slot_bytes)

    def pop_view(self, timeout_ms=-1):
        """Memoryview of the tail slot's payload WITHOUT copying.
        Auto-releases any previous pending view first — so a view (and
        arrays deserialized out of it) is valid until the NEXT
        pop_view/release_view on this ring."""
        if not self._h:
            return None
        self.release_view()
        n = self._c_uint64()
        ptr = self._lib.ring_pop_view(self._h, self._byref(n),
                                      timeout_ms)
        if not ptr:
            return None
        self._pending = True
        arr = (self._c_ubyte * n.value).from_address(ptr)
        return memoryview(arr).cast("B")

    def release_view(self):
        if self._pending and self._h:
            self._lib.ring_pop_release(self._h)
            self._pending = False

    def close(self):
        if self._h:
            self._lib.ring_close(self._h)
            self._h = None
        if self._creator:
            self._lib.ring_unlink(self.name)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    """reference: paddle.io.get_worker_info (dataloader/worker.py)."""
    return _worker_info


def _fetch_samples(dataset, indices, worker_id, on_bad_sample):
    """Per-sample fetch with the chaos `io_fetch` site and the
    per-sample error policy: "raise" keeps today's fail-the-epoch
    behavior; "skip" drops the failing sample and reports (samples,
    n_skipped, last traceback) so the trainer can count it instead of
    killing the epoch on one corrupt record."""
    skip = on_bad_sample == "skip"
    out, skipped, err = [], 0, None
    for i in indices:
        try:
            if _chaos._armed:
                _chaos.hit("io_fetch", worker=worker_id)
            out.append(dataset[i])
        except (SystemExit, KeyboardInterrupt):
            raise
        except Exception as e:
            # tagged chaos exceptions are runtime-FAULT injection
            # (raise/enospc/resource_exhausted, a downgraded crash),
            # NOT bad records — the skip policy swallowing one would
            # make the chaos/* triggered counters claim faults with
            # no observable effect. ChaosBadSample IS the bad-record
            # simulation and stays skippable.
            if not skip or getattr(e, "_paddle_chaos_fault", False):
                raise
            skipped += 1
            import traceback

            err = traceback.format_exc()
    return out, skipped, err


def _worker_loop(worker_id, num_workers, dataset, collate_fn, ring_name,
                 slots, slot_bytes, index_queue, worker_init_fn,
                 iterable_mode, batch_size, drop_last, base_seed,
                 default_collate=False, on_bad_sample="raise"):
    """Runs in the child process: pull work, compute, push to the ring."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset,
                              seed=base_seed + worker_id)
    np.random.seed((base_seed + worker_id) % (2 ** 31))
    ring = ShmRing(ring_name, slots, slot_bytes, create=False)
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        if iterable_mode:
            # each worker consumes a strided shard of the iterable
            # (reference _IterableDatasetStopIteration contract); the
            # index queue carries per-epoch start markers so persistent
            # workers serve any number of epochs
            import itertools

            while True:
                item = index_queue.get()
                if item == "QUIT":
                    break
                try:
                    it = itertools.islice(iter(dataset), worker_id, None,
                                          num_workers)
                    if batch_size is None:
                        # batch_size=None: raw per-sample values, no
                        # collate (matches the single-process path)
                        for sample in it:
                            _push_batch(ring, sample)
                    else:
                        while True:
                            batch = list(itertools.islice(it, batch_size))
                            if not batch or (len(batch) < batch_size
                                             and drop_last):
                                break
                            _push_batch(ring, collate_fn(batch))
                except Exception as e:
                    import traceback

                    ring.push(_ERR + pickle.dumps(
                        (type(e).__name__, traceback.format_exc())))
                ring.push(_EOF)
            return
        # map mode. With PADDLE_IO_OVERLAP_DECODE=1 (default) a decode
        # thread fetches the NEXT index batch's samples while this
        # thread collates the current one into the ring (or blocks on
        # ring backpressure) — the queue keeps marker/batch order, so
        # EOF/QUIT handling and the fed-log restart contract are
        # unchanged. With overlap off, _next_work inlines the fetch.
        q_local = None
        if _overlap_decode_enabled():
            import queue as _qmod

            q_local = _qmod.Queue(maxsize=1)

            def _decode_loop():
                while True:
                    item = index_queue.get()
                    if item is None or item == "QUIT":
                        q_local.put((item, None))
                        if item == "QUIT":
                            return
                        continue
                    try:
                        q_local.put(("BATCH", _fetch_samples(
                            dataset, item, worker_id, on_bad_sample)))
                    except BaseException as e:
                        import traceback

                        q_local.put(("ERR", (type(e).__name__,
                                             traceback.format_exc())))

            threading.Thread(target=_decode_loop, daemon=True,
                             name="paddle-io-decode").start()

        def _next_work():
            if q_local is not None:
                return q_local.get()
            item = index_queue.get()
            if item is None or item == "QUIT":
                return item, None
            try:
                return "BATCH", _fetch_samples(dataset, item,
                                               worker_id, on_bad_sample)
            except BaseException as e:
                import traceback

                return "ERR", (type(e).__name__,
                               traceback.format_exc())

        while True:
            kind, payload = _next_work()
            if kind is None:
                ring.push(_EOF)
                # persistent workers loop for the next epoch's indices
                continue
            if kind == "QUIT":
                break
            if kind == "ERR":  # surface the fetch error to the trainer
                ring.push(_ERR + pickle.dumps(payload))
                continue
            samples, skipped, err = payload
            try:
                if skipped:
                    # skip-and-count: the trainer must still see ONE
                    # payload for this fed batch (ring order), so the
                    # partial batch (or None when every sample failed)
                    # rides a _SKP frame with the skip count
                    batch = collate_fn(samples) if samples else None
                    ring.push(_SKP + pickle.dumps(
                        (batch, skipped, err), protocol=5))
                # default collate + zero-copy: stack straight into the
                # slot (one copy per sample total)
                elif not (default_collate and _zero_copy_enabled()
                          and _try_push_stacked(ring, samples)):
                    _push_batch(ring, collate_fn(samples))
            except Exception as e:  # surface the error to the trainer
                import traceback

                ring.push(_ERR + pickle.dumps(
                    (type(e).__name__, traceback.format_exc())))
    finally:
        ring.close()


class MultiprocessLoader:
    """Trainer-side controller: W workers, W rings, ordered pops.

    SUPERVISED (map-style pipelines): a worker that dies (OOM-killed,
    chaos crash) or wedges past `wedge_timeout_s` is restarted up to
    `restarts` times EACH (per-worker budgets — one crashy worker
    can't starve the others') with a FRESH ring + index queue, and
    every index batch it was fed but the trainer has not yet popped
    is re-fed in
    order off the per-worker fed-log — global batch order is preserved
    by construction (pops still ride ring w for batch k == w mod W).
    Iterable-mode shards have no replayable cursor and keep the
    fail-fast raise. Counters: io/workers/{restarts,leaked},
    io/bad_samples; flight events io_worker_restart / io_bad_sample."""

    def __init__(self, dataset, collate_fn, num_workers, prefetch_factor,
                 slot_mb, worker_init_fn, timeout, persistent,
                 iterable_mode=False, batch_size=1, drop_last=False,
                 default_collate=False, on_bad_sample="raise",
                 restarts=2, wedge_timeout_s=0.0):
        import collections
        import multiprocessing as mp

        self._mp = mp.get_context("fork")
        self.num_workers = num_workers
        self.timeout_ms = int(timeout * 1000) if timeout else -1
        self.persistent = persistent
        self.iterable_mode = iterable_mode
        self._slot_bytes = slot_mb * 1024 * 1024
        slots = max(2, prefetch_factor)
        self._slots = slots
        self._busy = False
        self._base = f"/pdtpu_{os.getpid()}_{id(self)}"
        self.rings = []
        self.queues = []
        self.procs = []
        base_seed = np.random.randint(0, 2 ** 31 - 1)
        # everything a respawn needs (the dataset/collate refs fork
        # cleanly). base_seed is REUSED, which restores the
        # predecessor's INITIAL np.random state — but the respawn
        # resumes mid-stream, so draw-dependent __getitem__ transforms
        # (augmentation) diverge from the fault-free run after a
        # restart: recovery trades that corner of bit-identity for a
        # finished epoch, and io_worker_restart events mark where
        self._spawn = dict(
            dataset=dataset, collate_fn=collate_fn,
            worker_init_fn=worker_init_fn, batch_size=batch_size,
            drop_last=drop_last, base_seed=base_seed,
            default_collate=default_collate,
            on_bad_sample=on_bad_sample)
        # PER-WORKER restart budgets: one crashy worker must not
        # starve the others' supervision (the docstring contract is
        # "restarted up to `restarts` times" per worker)
        self._restart_budget = [max(0, int(restarts))] * num_workers
        self._wedge_ms = int(max(0.0, float(wedge_timeout_s)) * 1000)
        self._ring_gen = [0] * num_workers
        # per-worker index batches fed but not yet popped (map mode) —
        # the refeed source on restart
        self._fed_log = [collections.deque()
                         for _ in range(num_workers)]
        # rings replaced by a restart, kept MAPPED until the worker's
        # next delivered batch (see _restart_worker)
        self._retired_rings = [[] for _ in range(num_workers)]
        self._done_feeding = False
        # per-worker: did THIS epoch's end-of-epoch EOF already pop?
        # (a restart must not replay the None marker then — the fresh
        # worker's second EOF would surface as a garbage "batch" at
        # the start of the NEXT persistent epoch)
        self._eof_seen = [False] * num_workers
        for w in range(num_workers):
            ring, q, p = self._spawn_worker(w)
            self.rings.append(ring)
            self.queues.append(q)
            self.procs.append(p)

    def _spawn_worker(self, w):
        """Fork one worker on a fresh ring + queue (initial spawn and
        restart share this path)."""
        gen = self._ring_gen[w]
        ring_name = (f"{self._base}_{w}" if gen == 0
                     else f"{self._base}_{w}g{gen}")
        ring = ShmRing(ring_name, self._slots, self._slot_bytes,
                       create=True)
        q = self._mp.Queue()
        s = self._spawn
        p = self._mp.Process(
            target=_worker_loop,
            args=(w, self.num_workers, s["dataset"], s["collate_fn"],
                  ring_name, self._slots, self._slot_bytes, q,
                  s["worker_init_fn"], self.iterable_mode,
                  s["batch_size"], s["drop_last"], s["base_seed"],
                  s["default_collate"], s["on_bad_sample"]),
            daemon=True)
        p.start()
        return ring, q, p

    @staticmethod
    def _reap(p, grace=2.0):
        """terminate -> kill escalation with bounded joins; returns
        False when the process survived everything (leaked). ONE copy
        shared by restart and shutdown so the escalation discipline
        can't drift between them."""
        try:
            if not p.is_alive():
                p.join(0.5)
                return True
            p.terminate()
            p.join(grace)
            if p.is_alive():
                p.kill()  # SIGKILL: wedged in C code / a chaos stall
                p.join(1.0)
        except Exception:
            pass
        return not p.is_alive()

    def _restart_worker(self, w, why):
        """Replace a dead/wedged worker: kill what's left of it, drop
        its ring (possibly holding a torn half-pushed batch), respawn
        on a fresh ring, and re-feed its outstanding index batches in
        their original order."""
        from ..core import monitor as _monitor
        from ..monitor import flight as _flight

        self._restart_budget[w] -= 1
        self._reap(self.procs[w])
        # release the dead worker's queue (feeder thread + pipe fds):
        # dropping the reference alone leaks them until GC, and a
        # queue with unflushed items can block interpreter exit on
        # the feeder join
        try:
            old_q = self.queues[w]
            old_q.cancel_join_thread()
            old_q.close()
        except Exception:
            pass
        # do NOT close (munmap) the old ring yet: the last batch this
        # worker delivered may be a zero-copy view still aliasing a
        # slot — by contract it stays valid until the worker's NEXT
        # pop, so the unmap is deferred to exactly that point (the
        # new ring uses a fresh shm name, so no collision)
        self._retired_rings[w].append(self.rings[w])
        self._ring_gen[w] += 1
        ring, q, proc = self._spawn_worker(w)
        self.rings[w] = ring
        self.queues[w] = q
        self.procs[w] = proc
        refed = list(self._fed_log[w])
        for idxs in refed:
            q.put(list(idxs))
        if self._done_feeding and not self._eof_seen[w]:
            q.put(None)  # replay the epoch-end marker too
        _monitor.stat_add("io/workers/restarts", 1)
        _flight.record("io_worker_restart", worker=w, why=why,
                       refed=len(refed),
                       restarts_left=self._restart_budget[w])

    def run_epoch(self, index_batches):
        """Feed indices round-robin with a bounded in-flight window;
        yield deserialized batches in order. Batch k is assigned to
        worker k % W and popped from ring k % W, so pops see each
        ring's batches exactly in global order and every ring ends the
        epoch with exactly one EOF marker. An early-exited epoch
        (break / generator close) is drained in the finally so
        persistent workers start the next epoch with clean rings."""
        if self._busy:
            raise RuntimeError(
                "this DataLoader's persistent workers are already "
                "serving an iterator — finish or close it before "
                "starting another")
        self._busy = True
        try:
            if self.iterable_mode:
                yield from self._run_iterable()
                return
            it = iter(index_batches)
            fed = popped = 0
            window = self.num_workers * self._slots
            self._done_feeding = False
            for d in self._fed_log:
                d.clear()
            self._eof_seen = [False] * self.num_workers

            def feed():
                nonlocal fed
                while not self._done_feeding and fed - popped < window:
                    try:
                        idxs = next(it)
                    except StopIteration:
                        self._done_feeding = True
                        for q in self.queues:
                            q.put(None)  # epoch end marker
                        return
                    w = fed % self.num_workers
                    idxs = list(idxs)
                    self.queues[w].put(idxs)
                    self._fed_log[w].append(idxs)
                    fed += 1

            feed()
            try:
                while popped < fed or not self._done_feeding:
                    batch = self._pop_checked(
                        popped % self.num_workers)
                    popped += 1
                    feed()
                    if batch is _SKIPPED:
                        continue  # every sample failed: drop, don't
                        # yield (on_bad_sample="skip")
                    yield batch
            finally:
                # early exit: flush remaining fed batches + all EOFs
                # (skip when _pop_checked already shut us down, and at
                # interpreter shutdown, where module globals the drain
                # needs are already torn down)
                if self.rings and not _sys.is_finalizing():
                    if not self._done_feeding:
                        self._done_feeding = True
                        for q in self.queues:
                            q.put(None)
                    while popped < fed:
                        self._pop_checked(popped % self.num_workers)
                        popped += 1
                    for w in range(self.num_workers):
                        self._pop_checked(w)  # EOF markers
        finally:
            self._busy = False

    def _run_iterable(self):
        for q in self.queues:
            q.put("EPOCH")  # wake (persistent) workers for this epoch
        live = set(range(self.num_workers))
        w = 0
        try:
            while live:
                if w not in live:
                    w = (w + 1) % self.num_workers
                    continue
                batch = self._pop_checked(w)
                if batch is _EOF:
                    live.discard(w)
                elif batch is not _SKIPPED:
                    yield batch
                w = (w + 1) % self.num_workers
        finally:
            # early exit: drain until every worker's EOF arrives
            # (skip when _pop_checked already shut us down, or at
            # interpreter shutdown)
            while live and self.rings and not _sys.is_finalizing():
                for w in list(live):
                    batch = self._pop_checked(w)
                    if batch is _EOF:
                        live.discard(w)

    def _can_restart(self, w):
        return not self.iterable_mode and self._restart_budget[w] > 0

    def _pop_checked(self, w):
        """Pop + decode worker `w`'s ring with liveness polling: a
        worker killed by the OS (or crashed outside the guarded
        region) is RESTARTED with its outstanding batches re-fed when
        the supervision budget allows (map mode), else raises — never
        hangs; a worker alive but silent past the wedge timeout
        (PADDLE_IO_WORKER_TIMEOUT_S) is treated the same way. Returns
        the decoded batch, the _EOF marker, or _SKIPPED (a fully
        failed batch under on_bad_sample="skip"). Zero-copy batches
        alias the ring slot; the slot is auto-released on the NEXT pop
        of the same ring (pop_view), so a yielded batch stays valid
        until that worker's next batch is fetched — W batches of slack
        in the round-robin order."""
        import time as _t

        from ..core import monitor as _monitor
        from ..monitor import flight as _flight

        tick = 2000
        if self._wedge_ms > 0:
            tick = max(50, min(tick, self._wedge_ms // 2))
        waited = 0        # total wait for THIS batch (user timeout)
        wedge_waited = 0  # silence since last progress/restart
        t0 = _t.perf_counter()
        while True:
            if not self.procs:
                raise RuntimeError("DataLoader was shut down while "
                                   "batches were still pending")
            budget = (self.timeout_ms if self.timeout_ms > 0
                      else tick)
            view = self.rings[w].pop_view(min(budget, tick))
            if view is not None:
                # this pop is the contract point where the worker's
                # PREVIOUS batch becomes invalid — a pre-restart ring
                # kept mapped for that batch can be unmapped now
                for r in self._retired_rings[w]:
                    try:
                        r.close()
                    except Exception:
                        pass
                self._retired_rings[w] = []
                break
            waited += tick
            wedge_waited += tick
            # the user's per-batch timeout is TOTAL wait including
            # any restarts — only the wedge timer resets on restart,
            # or DataLoader(timeout=) would silently stretch to
            # (restarts+1) x its bound
            if self.timeout_ms > 0 and waited >= self.timeout_ms:
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader timed out after {self.timeout_ms} ms "
                    "waiting for a worker batch")
            dead = [i for i, p in enumerate(self.procs)
                    if not p.is_alive()]
            wedged = (self._wedge_ms > 0
                      and wedge_waited >= self._wedge_ms
                      and w not in dead)
            if dead and not self.iterable_mode \
                    and all(self._restart_budget[i] > 0 for i in dead):
                # restart every dead worker now (not just the one
                # being popped) — their outstanding batches re-feed
                # while this pop keeps waiting
                for i in dead:
                    self._restart_worker(i, why="died")
                wedge_waited = 0
                continue
            if wedged and self._can_restart(w):
                self._restart_worker(w, why="wedged")
                wedge_waited = 0
                continue
            if dead or wedged:
                self.shutdown()
                raise RuntimeError(
                    "a DataLoader worker process "
                    + ("died unexpectedly (killed or crashed)"
                       if dead else
                       "wedged past PADDLE_IO_WORKER_TIMEOUT_S")
                    + (" (iterable-mode pipelines are fail-fast by "
                       "design)" if self.iterable_mode else
                       " and its restart budget (worker_restarts) "
                       "is exhausted")
                    + " — see worker logs")
        # telemetry: ring-wait time (trainer blocked on workers) +
        # delivered payload bytes — io/ring_wait_us climbing while
        # step/time holds steady means the pipeline is input-bound
        _monitor.stat_add("io/ring_wait_us",
                          int((_t.perf_counter() - t0) * 1e6))
        _monitor.stat_add("io/ring_bytes", int(view.nbytes))
        batch = _decode_view(view)
        if batch is not None:
            self._note_popped(w)
            return batch
        payload = bytes(view)
        view.release()
        self.rings[w].release_view()
        if payload == _EOF:
            self._eof_seen[w] = True
            return _EOF
        if payload.startswith(_ERR):
            name, tb = pickle.loads(payload[len(_ERR):])
            self.shutdown()
            raise RuntimeError(
                f"DataLoader worker raised {name}:\n{tb}")
        if payload.startswith(_SKP):
            batch, nskip, err = pickle.loads(payload[len(_SKP):])
            note_bad_samples(nskip, err, worker=w)
            self._note_popped(w)
            return _SKIPPED if batch is None else batch
        self._note_popped(w)
        return pickle.loads(payload)

    def _note_popped(self, w):
        """One fed index batch of worker w was delivered — it leaves
        the restart refeed window."""
        if not self.iterable_mode and self._fed_log[w]:
            self._fed_log[w].popleft()

    def shutdown(self):
        """Tear the pool down with a BOUNDED grace window: QUIT, join,
        escalate terminate -> kill, and COUNT any worker that survives
        all of it under io/workers/leaked — teardown on an exception
        mid-epoch must neither hang the trainer nor silently rely on
        daemon reaping at interpreter exit."""
        for q in self.queues:
            try:
                q.put("QUIT")
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=2)
        leaked = sum(0 if self._reap(p, grace=1.0) else 1
                     for p in self.procs)
        if leaked:
            from ..core import monitor as _monitor
            from ..monitor import flight as _flight

            _monitor.stat_add("io/workers/leaked", leaked)
            _flight.record("io_worker_leak", n=leaked)
        for q in self.queues:
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass
        for r in self.rings:
            r.close()
        for rs in self._retired_rings:
            for r in rs:
                try:
                    r.close()
                except Exception:
                    pass
        self._retired_rings = [[] for _ in range(self.num_workers)]
        self.procs, self.queues, self.rings = [], [], []

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
