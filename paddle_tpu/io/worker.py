"""Multiprocess DataLoader workers with shared-memory transport.

Parity target: python/paddle/fluid/dataloader/dataloader_iter.py:326
(_DataLoaderIterMultiProcess), worker.py (worker loop + WorkerInfo),
and the mmap shared-memory tensor path
(paddle/fluid/memory/allocation/mmap_allocator.cc).

TPU-native design: each worker OWNS one C shared-memory SPSC ring
(utils/cpp/shm_ring.cc — lock-free head/tail atomics); batches are
pickled (protocol 5) straight into the ring slot, so worker->trainer
transport never touches a pipe. Batch i is assigned to worker i % W
and the trainer pops rings in that order — global batch order is
deterministic regardless of worker speed (the reference's reorder
buffer, by construction). The trainer thread then hands bytes to PJRT
host->device transfer.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import queue
import threading

import numpy as np

_EOF = b"\x00PDEOF"
_ERR = b"\x00PDERR"

_lib = None
_lib_lock = threading.Lock()


def _ring_lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            from ..utils.cpp_extension import load

            src = os.path.join(os.path.dirname(__file__), "..", "utils",
                               "cpp", "shm_ring.cc")
            lib = load("shm_ring", [os.path.abspath(src)],
                       extra_ldflags=["-lrt"])
            lib.ring_open.restype = ctypes.c_void_p
            lib.ring_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64, ctypes.c_int]
            lib.ring_push.restype = ctypes.c_int
            lib.ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int64]
            lib.ring_pop.restype = ctypes.c_int64
            lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64, ctypes.c_int64]
            lib.ring_close.argtypes = [ctypes.c_void_p]
            lib.ring_unlink.argtypes = [ctypes.c_char_p]
            _lib = lib
        return _lib


class ShmRing:
    """One SPSC ring in POSIX shared memory (ctypes over shm_ring.cc)."""

    def __init__(self, name, slots, slot_bytes, create):
        self._lib = _ring_lib()
        self.name = name.encode()
        self.slot_bytes = slot_bytes
        self._h = self._lib.ring_open(self.name, slots, slot_bytes,
                                      1 if create else 0)
        if not self._h:
            raise OSError(f"shm ring {name} open failed")
        self._creator = create
        self._buf = None  # lazy: workers only push; don't hold 64MB

    def push(self, data: bytes, timeout_ms=-1):
        rc = self._lib.ring_push(self._h, data, len(data), timeout_ms)
        if rc == -2:
            raise ValueError(
                f"batch of {len(data)} bytes exceeds the shared-memory "
                f"slot ({self.slot_bytes}B) — raise "
                "FLAGS_dataloader_shm_slot_mb or shrink the batch")
        return rc == 0

    def pop(self, timeout_ms=-1):
        if self._buf is None:
            self._buf = ctypes.create_string_buffer(self.slot_bytes)
        n = self._lib.ring_pop(self._h, self._buf, self.slot_bytes,
                               timeout_ms)
        if n == -1:
            return None
        if n < 0:
            raise OSError(f"ring_pop error {n}")
        return self._buf.raw[:n]

    def close(self):
        if self._h:
            self._lib.ring_close(self._h)
            self._h = None
        if self._creator:
            self._lib.ring_unlink(self.name)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    """reference: paddle.io.get_worker_info (dataloader/worker.py)."""
    return _worker_info


def _worker_loop(worker_id, num_workers, dataset, collate_fn, ring_name,
                 slots, slot_bytes, index_queue, worker_init_fn,
                 iterable_mode, batch_size, drop_last, base_seed):
    """Runs in the child process: pull work, compute, push to the ring."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset,
                              seed=base_seed + worker_id)
    np.random.seed((base_seed + worker_id) % (2 ** 31))
    ring = ShmRing(ring_name, slots, slot_bytes, create=False)
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        if iterable_mode:
            # each worker consumes a strided shard of the iterable
            # (reference _IterableDatasetStopIteration contract); the
            # index queue carries per-epoch start markers so persistent
            # workers serve any number of epochs
            import itertools

            while True:
                item = index_queue.get()
                if item == "QUIT":
                    break
                try:
                    it = itertools.islice(iter(dataset), worker_id, None,
                                          num_workers)
                    if batch_size is None:
                        # batch_size=None: raw per-sample values, no
                        # collate (matches the single-process path)
                        for sample in it:
                            ring.push(pickle.dumps(sample, protocol=5))
                    else:
                        while True:
                            batch = list(itertools.islice(it, batch_size))
                            if not batch or (len(batch) < batch_size
                                             and drop_last):
                                break
                            ring.push(pickle.dumps(collate_fn(batch),
                                                   protocol=5))
                except Exception as e:
                    import traceback

                    ring.push(_ERR + pickle.dumps(
                        (type(e).__name__, traceback.format_exc())))
                ring.push(_EOF)
            return
        while True:
            item = index_queue.get()
            if item is None:
                ring.push(_EOF)
                # persistent workers loop for the next epoch's indices
                continue
            if item == "QUIT":
                break
            try:
                samples = [dataset[i] for i in item]
                payload = pickle.dumps(collate_fn(samples), protocol=5)
                ring.push(payload)
            except Exception as e:  # surface the error to the trainer
                import traceback

                ring.push(_ERR + pickle.dumps(
                    (type(e).__name__, traceback.format_exc())))
    finally:
        ring.close()


class MultiprocessLoader:
    """Trainer-side controller: W workers, W rings, ordered pops."""

    def __init__(self, dataset, collate_fn, num_workers, prefetch_factor,
                 slot_mb, worker_init_fn, timeout, persistent,
                 iterable_mode=False, batch_size=1, drop_last=False):
        import multiprocessing as mp

        self._mp = mp.get_context("fork")
        self.num_workers = num_workers
        self.timeout_ms = int(timeout * 1000) if timeout else -1
        self.persistent = persistent
        self.iterable_mode = iterable_mode
        slot_bytes = slot_mb * 1024 * 1024
        slots = max(2, prefetch_factor)
        self._slots = slots
        self._busy = False
        base = f"/pdtpu_{os.getpid()}_{id(self)}"
        self.rings = []
        self.queues = []
        self.procs = []
        base_seed = np.random.randint(0, 2 ** 31 - 1)
        for w in range(num_workers):
            ring_name = f"{base}_{w}"
            ring = ShmRing(ring_name, slots, slot_bytes, create=True)
            q = self._mp.Queue()
            p = self._mp.Process(
                target=_worker_loop,
                args=(w, num_workers, dataset, collate_fn, ring_name,
                      slots, slot_bytes, q, worker_init_fn,
                      iterable_mode, batch_size, drop_last, base_seed),
                daemon=True)
            p.start()
            self.rings.append(ring)
            self.queues.append(q)
            self.procs.append(p)

    def run_epoch(self, index_batches):
        """Feed indices round-robin with a bounded in-flight window;
        yield deserialized batches in order. Batch k is assigned to
        worker k % W and popped from ring k % W, so pops see each
        ring's batches exactly in global order and every ring ends the
        epoch with exactly one EOF marker. An early-exited epoch
        (break / generator close) is drained in the finally so
        persistent workers start the next epoch with clean rings."""
        if self._busy:
            raise RuntimeError(
                "this DataLoader's persistent workers are already "
                "serving an iterator — finish or close it before "
                "starting another")
        self._busy = True
        try:
            if self.iterable_mode:
                yield from self._run_iterable()
                return
            it = iter(index_batches)
            fed = popped = 0
            window = self.num_workers * self._slots
            done_feeding = False

            def feed():
                nonlocal fed, done_feeding
                while not done_feeding and fed - popped < window:
                    try:
                        idxs = next(it)
                    except StopIteration:
                        done_feeding = True
                        for q in self.queues:
                            q.put(None)  # epoch end marker
                        return
                    self.queues[fed % self.num_workers].put(list(idxs))
                    fed += 1

            feed()
            try:
                while popped < fed or not done_feeding:
                    payload = self._pop_checked(
                        self.rings[popped % self.num_workers])
                    popped += 1
                    feed()
                    yield pickle.loads(payload)
            finally:
                # early exit: flush remaining fed batches + all EOFs
                # (skip when _pop_checked already shut us down)
                if self.rings:
                    if not done_feeding:
                        done_feeding = True
                        for q in self.queues:
                            q.put(None)
                    while popped < fed:
                        self._pop_checked(
                            self.rings[popped % self.num_workers])
                        popped += 1
                    for r in self.rings:
                        self._pop_checked(r)  # EOF markers
        finally:
            self._busy = False

    def _run_iterable(self):
        for q in self.queues:
            q.put("EPOCH")  # wake (persistent) workers for this epoch
        live = set(range(self.num_workers))
        w = 0
        try:
            while live:
                if w not in live:
                    w = (w + 1) % self.num_workers
                    continue
                payload = self._pop_checked(self.rings[w])
                if payload == _EOF:
                    live.discard(w)
                else:
                    yield pickle.loads(payload)
                w = (w + 1) % self.num_workers
        finally:
            # early exit: drain until every worker's EOF arrives
            # (skip when _pop_checked already shut us down)
            while live and self.rings:
                for w in list(live):
                    payload = self._pop_checked(self.rings[w])
                    if payload == _EOF:
                        live.discard(w)

    def _pop_checked(self, ring):
        """Pop with liveness polling: a worker killed by the OS (or
        crashed outside the guarded region) must raise, not hang."""
        tick = 2000
        waited = 0
        while True:
            budget = (self.timeout_ms if self.timeout_ms > 0
                      else tick)
            payload = ring.pop(min(budget, tick))
            if payload is not None:
                break
            waited += tick
            if self.timeout_ms > 0 and waited >= self.timeout_ms:
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader timed out after {self.timeout_ms} ms "
                    "waiting for a worker batch")
            if any(not p.is_alive() for p in self.procs):
                self.shutdown()
                raise RuntimeError(
                    "a DataLoader worker process died unexpectedly "
                    "(killed or crashed) — see worker logs")
        if payload.startswith(_ERR):
            name, tb = pickle.loads(payload[len(_ERR):])
            self.shutdown()
            raise RuntimeError(
                f"DataLoader worker raised {name}:\n{tb}")
        return payload

    def shutdown(self):
        for q in self.queues:
            try:
                q.put("QUIT")
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=2)
            if p.is_alive():
                p.terminate()
        for r in self.rings:
            r.close()
        self.procs, self.queues, self.rings = [], [], []

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
