"""Shared runtime for the distributed linear-algebra tier.

Everything dist algorithms need to ride the production spine lives
here, in one place:

- `Grid`: the 2D (row axis x col axis) process grid carved out of the
  live Fleet mesh (PADDLE_LINALG_AXES override), the SUMMA layout of
  arxiv 2112.09017 expressed as mesh axis names.
- PTA05x spec lints on every ShardedMatrix layout BEFORE compile
  (structural errors always raise; findings ride the analysis
  Finding/Report counters when PADDLE_ANALYSIS/PADDLE_SANITIZE arms
  them).
- the program cache + compile path: programs lower through jax.jit
  like every other subsystem and consult the PR-8 persistent compile
  cache (`linalg:<label>` entries, mesh device assignment as a digest
  leg), with `linalg_compile` flight spans.
- the dispatch path: `linalg_dispatch` chaos site, `linalg` flight
  in-flight spans (watchdog-visible), and the
  linalg/{matmuls,factorizations,eigensolves,bytes} counters.
- trace-level broadcast/psum/all_gather helpers that route through
  `distributed/collective.py` inside shard_map bodies, so the
  existing comm/<op>/{calls,bytes} telemetry prices the algorithm's
  collective traffic for free.
"""
from __future__ import annotations

import math
import os
import time as _time
from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp

from ...core import monitor as _monitor
from ...core.tensor import Tensor
from ...distributed import mesh as _mesh_mod
from ...monitor import chaos as _chaos
from ...monitor import flight as _flight
from ...monitor import perf as _perf
from ...monitor import sanitize as _sanitize

__all__ = ["Grid", "grid", "lint_spec", "compile_program", "dispatch",
           "bcast", "psum", "gather", "axes_group",
           "clear_program_cache"]

# compiled dist programs, (label, mesh sig, arg sig) -> executable —
# same LRU discipline as cost_model (the executables pin device memory
# for their constants, so a sweep over many shapes must not grow this
# without bound)
_PROGRAMS_MAX = 32
_programs: OrderedDict = OrderedDict()

# one Group per axis tuple: collective.py groups are cheap but
# registered forever in mesh._groups, so per-compile creation would
# leak registry entries across a planner sweep
_axis_groups: dict = {}


def axes_group(axes):
    """The collective Group for a tuple of mesh axis names."""
    axes = tuple(axes)
    g = _axis_groups.get(axes)
    if g is None:
        g = _mesh_mod.new_group_for_axes(axes)
        _axis_groups[axes] = g
    return g


class Grid:
    """A 2D process grid (rows x cols) over the live mesh. `cx` may be
    None: a 1D grid (all parallelism on rows) — the tall-skinny /
    small-world degenerate SUMMA case."""

    def __init__(self, mesh, rx, cx):
        self.mesh = mesh
        self.rx = rx
        self.cx = cx

    @property
    def px(self):
        return int(self.mesh.shape[self.rx])

    @property
    def py(self):
        return int(self.mesh.shape[self.cx]) if self.cx else 1

    @property
    def nranks(self):
        return self.px * self.py

    def row_axes(self):
        """Axes a ROW of the grid spans (broadcast within a row goes
        along the COLUMN axis)."""
        return (self.cx,) if self.cx else ()

    def col_axes(self):
        return (self.rx,)

    def all_axes(self):
        return (self.rx, self.cx) if self.cx else (self.rx,)

    def block_spec(self):
        """P(rx, cx): the 2D block layout."""
        from jax.sharding import PartitionSpec as P

        return P(self.rx, self.cx) if self.cx else P(self.rx, None)

    def row_spec(self):
        """P((rx, cx), None): 1D block-row layout over the whole
        grid (tall-skinny TSQR layout)."""
        from jax.sharding import PartitionSpec as P

        return P(self.all_axes() if self.cx else self.rx, None)

    def sig(self):
        """Cache/digest signature: axis names + sizes + the device
        assignment (reshaped/reordered meshes must not collide in the
        persistent compile cache — the DistributedTrainStepCompiler
        contract)."""
        return (self.rx, self.cx,
                tuple(int(self.mesh.shape[a])
                      for a in self.mesh.axis_names),
                tuple(int(d.id) for d in self.mesh.devices.flat))

    def __repr__(self):
        return (f"Grid({self.px}x{self.py}, row_axis={self.rx!r}, "
                f"col_axis={self.cx!r})")


def grid(mesh=None, row_axis=None, col_axis=None):
    """Resolve the process grid from the live Fleet mesh.

    Default axis pick: PADDLE_LINALG_AXES='rx,cx' when set, else the
    first two mesh axes with size > 1 in mesh order (one -> 1D grid,
    none -> 1x1 on the first axis). Explicit row_axis/col_axis win."""
    mesh = mesh if mesh is not None else _mesh_mod.ensure_mesh()
    names = tuple(mesh.axis_names)
    env = os.environ.get("PADDLE_LINALG_AXES")
    if row_axis is None and col_axis is None and env:
        parts = [p.strip() for p in env.split(",") if p.strip()]
        row_axis = parts[0] if parts else None
        col_axis = parts[1] if len(parts) > 1 else None
    if row_axis is None:
        big = [a for a in names if int(mesh.shape[a]) > 1]
        row_axis = big[0] if big else names[0]
        if col_axis is None:
            col_axis = big[1] if len(big) > 1 else None
    for a in (row_axis, col_axis):
        if a is not None and a not in names:
            raise ValueError(
                f"paddle.linalg.dist: grid axis {a!r} is not a mesh "
                f"axis (mesh axes: {list(names)}) — set "
                "PADDLE_LINALG_AXES or pass row_axis/col_axis")
    if col_axis == row_axis:
        raise ValueError(
            "paddle.linalg.dist: row_axis and col_axis must be "
            f"distinct mesh axes (both {row_axis!r})")
    return Grid(mesh, row_axis, col_axis)


def lint_spec(spec, shape, mesh, *, name="matrix", where="linalg.dist"):
    """PTA05x sharding lints on a ShardedMatrix spec BEFORE compile.

    Structural errors (unknown axis PTA050, indivisible dim PTA051,
    rank mismatch PTA052) always raise — the dist algorithms cannot
    run on them and shard_map would only fail later and worse. The
    findings additionally ride the analysis/<code>/findings counters
    when PADDLE_ANALYSIS=1 or PADDLE_SANITIZE=sharding is armed (and
    ONLY then: the disarmed path must leave zero counters — the
    bench.py provenance contract)."""
    from ...analysis.sharding import check_spec

    mesh_axes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    report = check_spec(spec, shape, mesh_axes, name=name, where=where)
    if report.findings:
        armed = False
        try:
            from ...analysis import enabled as _analysis_enabled

            armed = _sanitize._sharding or _analysis_enabled()
        except Exception:
            pass
        if armed:
            report.record()
        if report.errors:
            raise ValueError(
                "paddle.linalg.dist: PTA05x sharding lint failed for "
                f"{name}:\n"
                + "\n".join(f.format() for f in report.errors))
    return report


def _arg_sig(args):
    return tuple((tuple(int(d) for d in np.shape(a)),
                  str(getattr(a, "dtype", np.asarray(a).dtype)))
                 for a in args)


def shard_map(body, mesh, in_specs, out_specs):
    """The cross-version shard_map shim, shared with ring attention
    (distributed.mesh.shard_map_compat)."""
    return _mesh_mod.shard_map_compat(body, mesh, in_specs,
                                      out_specs)


def compile_program(label, build, grid_, args, extra_key=()):
    """Compiled executable for a dist program.

    `build()` returns the traceable global-array function (usually a
    shard_map island). Keyed by (label, grid signature, arg
    shapes/dtypes, extra_key); fresh compiles lower through jax.jit
    and consult the persistent compile cache under `linalg:<label>`
    with the grid signature as a digest leg — a planner/bench rerun
    (or replica N of a fleet) boots warm."""
    key = (label, grid_.sig(), _arg_sig(args), tuple(extra_key))
    ent = _programs.get(key)
    if ent is not None:
        _programs.move_to_end(key)
        _monitor.stat_add("linalg/program_cache/hits", 1)
        return ent
    t0 = _time.perf_counter()
    tok = _flight.begin("linalg_compile", label) \
        if _flight.recorder.enabled else None
    try:
        lowered = jax.jit(build()).lower(*args)
        from ...jit import persistent_cache as _pcache

        if _pcache.enabled():
            compiled, _ = _pcache.load_or_compile(
                lowered, f"linalg:{label}",
                extra=(repr(grid_.sig()),))
        else:
            compiled = lowered.compile()
    finally:
        _flight.end(tok)
    _monitor.stat_add("linalg/compiles", 1)
    _monitor.stat_add("linalg/compile_us",
                      int((_time.perf_counter() - t0) * 1e6))
    # roofline ledger: the compiled executable is already in hand on
    # this fresh-compile path, so the cost capture is free (no extra
    # backend compile, unlike the jit/serving capture sites)
    _perf.record_program_cost(f"linalg:{label}", compiled)
    _programs[key] = compiled
    while len(_programs) > _PROGRAMS_MAX:
        _programs.popitem(last=False)
    return compiled


def clear_program_cache():
    """Drop every cached dist executable (tests; mesh teardown)."""
    _programs.clear()


def _nbytes(arrs):
    n = 0
    for a in arrs:
        try:
            n += int(np.prod(np.shape(a))) * jnp.dtype(a.dtype).itemsize
        except Exception:
            pass
    return n


def dispatch(kind, label, compiled, args):
    """Run one compiled dist program through the production spine:
    `linalg_dispatch` chaos site, a watchdog-visible `linalg`
    in-flight flight span, and the linalg/{<kind>,bytes} counters
    (`kind` in matmuls/factorizations/eigensolves)."""
    nbytes = _nbytes(args)
    if _chaos._armed:
        _chaos.hit("linalg_dispatch", op=label)
    tok = _flight.begin("linalg", label, bytes=nbytes) \
        if _flight.recorder.enabled else None
    timing = _perf.dispatch_timing_enabled()
    t0 = _time.perf_counter() if timing else None
    try:
        out = compiled(*args)
        if timing:
            # block before the span closes so the flight `linalg`
            # span and the dispatch histogram both see device time
            jax.block_until_ready(out)
            _perf.observe_dispatch(
                f"linalg:{label}",
                int((_time.perf_counter() - t0) * 1e6))
    finally:
        _flight.end(tok)
    _monitor.stat_add(f"linalg/{kind}", 1)
    _monitor.stat_add("linalg/bytes",
                      nbytes + _nbytes(jax.tree_util.tree_leaves(out)))
    return out


# ---------------------------------------------------------------------------
# trace-level collectives: the distributed/collective.py surface, made
# convenient for shard_map bodies on raw per-shard arrays. Each helper
# wraps the shard in a Tensor and calls the instrumented module
# function, so comm/<op>/{calls,bytes} counters + flight events record
# the algorithm's analytic traffic at trace time (the established
# convention: bytes are the static per-rank payload).
# ---------------------------------------------------------------------------

def bcast(val, axes, src):
    """Broadcast `val` from group-local flat rank `src` across mesh
    `axes` (masked-psum broadcast — collective.broadcast's traced
    path). Identity on an empty axis tuple (1D-grid degenerate)."""
    axes = tuple(a for a in axes if a is not None)
    if not axes:
        return val
    from ...distributed import collective as C

    t = Tensor(val, stop_gradient=True, _internal=True)
    C.broadcast(t, src=int(src), group=axes_group(axes))
    return t._value


def psum(val, axes):
    """Sum-reduce `val` across mesh `axes` (collective.all_reduce's
    traced path)."""
    axes = tuple(a for a in axes if a is not None)
    if not axes:
        return val
    from ...distributed import collective as C

    t = Tensor(val, stop_gradient=True, _internal=True)
    C.all_reduce(t, group=axes_group(axes))
    return t._value


def gather(val, axes):
    """all_gather across mesh `axes`, stacked on a new leading dim of
    length prod(axis sizes), ordered row-major by axis order (== the
    group-local flat rank)."""
    axes = tuple(a for a in axes if a is not None)
    if not axes:
        return val[None]
    from ...distributed import collective as C

    parts = []
    C.all_gather(parts, Tensor(val, stop_gradient=True,
                               _internal=True),
                 group=axes_group(axes))
    return jnp.stack([p._value for p in parts], axis=0)


def flat_rank(grid_):
    """This shard's group-local flat rank on the grid, row-major —
    matches gather()'s leading-dim order and bcast()'s src index."""
    from jax import lax

    r = lax.axis_index(grid_.rx)
    if grid_.cx:
        r = r * grid_.py + lax.axis_index(grid_.cx)
    return r


def block_divisor(n, *counts):
    """Largest candidate block: gcd of the per-axis local extents."""
    g = 0
    for c in counts:
        g = math.gcd(g, n // c)
    return g
