"""Distributed dense factorizations on the Fleet mesh.

- `cholesky`: blocked RIGHT-LOOKING Cholesky on the 2D block layout.
  Per panel k: the (nb, nb) diagonal block is 2D-broadcast and
  factored redundantly (tiny), the owning grid column computes its
  panel rows with a local triangular solve, the panel replicates via
  a row broadcast + column all_gather (the collective tree), and
  every rank applies one local rank-nb trailing update on the MXU.
- `qr` (TSQR/CAQR): tall-skinny QR on the `rows` layout. Panel
  factorization is LOCAL (each rank QRs its block row); the R factors
  reduce through one all_gather tree and a second small QR; Q comes
  back from one local matmul. Communication is p * n^2 elements,
  independent of M.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import runtime
from .sharded import ShardedMatrix

__all__ = ["cholesky", "qr", "tsqr"]

CHOLESKY_BLOCK_CAP = 128


def _chol_block(N, grid_, block_size=None):
    g = runtime.block_divisor(N, grid_.px, grid_.py)
    if g <= 0:
        raise ValueError(
            f"paddle.linalg.dist.cholesky: matrix dim {N} does not "
            f"tile the {grid_.px}x{grid_.py} grid")
    if block_size:
        nb = int(block_size)
        if g % nb:
            raise ValueError(
                f"paddle.linalg.dist.cholesky: block_size {nb} must "
                f"divide gcd(N/px, N/py) = {g}")
        return nb
    return max(d for d in range(1, g + 1)
               if g % d == 0 and d <= CHOLESKY_BLOCK_CAP)


def _build_cholesky(grid_, N, nb, dtype):
    px, py = grid_.px, grid_.py
    rb, cb = N // px, N // py

    def body(a):
        i = lax.axis_index(grid_.rx)
        j = lax.axis_index(grid_.cx) if grid_.cx else 0
        L = jnp.zeros_like(a)
        gr = jnp.arange(N)
        for k in range(N // nb):
            g0 = k * nb
            ik, jk = g0 // rb, g0 // cb
            # (nb, nb) diagonal block, 2D broadcast from its owner
            d = lax.slice(a, (g0 % rb, g0 % cb),
                          (g0 % rb + nb, g0 % cb + nb))
            d = runtime.bcast(d, grid_.all_axes(), ik * py + jk)
            lkk = jnp.linalg.cholesky(d)
            # panel: this rank's candidate rows, A[:, k] @ L_kk^{-T}.
            # Non-owner columns compute garbage the broadcast masks.
            pan = lax.slice_in_dim(a, g0 % cb, g0 % cb + nb, axis=1)
            pan = jax.scipy.linalg.solve_triangular(
                lkk, pan.T, lower=True).T
            pan = runtime.bcast(pan, grid_.row_axes(), jk)
            # replicate the full (N, nb) panel: the diagonal rows of
            # A_kk @ L_kk^{-T} are exactly L_kk, rows above are stale
            # -> masked to zero
            pfull = runtime.gather(pan, grid_.col_axes())
            pfull = pfull.reshape(N, nb)
            pfull = jnp.where(gr[:, None] < g0, 0, pfull)
            # the diagonal rows of A_kk @ L_kk^{-T} equal L_kk only up
            # to solve roundoff — substitute the exactly-triangular
            # factor so L's upper triangle is exactly zero
            pfull = pfull.at[g0:g0 + nb, :].set(lkk)
            mine = lax.dynamic_slice_in_dim(pfull, i * rb, rb, axis=0)
            cur = lax.slice_in_dim(L, g0 % cb, g0 % cb + nb, axis=1)
            written = jnp.where(jnp.equal(j, jk), mine, cur)
            L = L.at[:, g0 % cb:g0 % cb + nb].set(written)
            # trailing update A -= L[:,k] L[:,k]^T restricted to rows
            # AND cols past the panel (earlier rows/cols zero out)
            pm = jnp.where(gr[:, None] < g0 + nb, 0, pfull)
            rows = lax.dynamic_slice_in_dim(pm, i * rb, rb, axis=0)
            cols = lax.dynamic_slice_in_dim(pm, j * cb, cb, axis=0)
            a = a - jnp.matmul(
                rows, cols.T,
                preferred_element_type=jnp.float32).astype(a.dtype)
        return L

    spec = grid_.block_spec()

    def fn(a):
        return runtime.shard_map(body, grid_.mesh, (spec,), spec)(a)

    return fn


def cholesky(a: ShardedMatrix, block_size=None) -> ShardedMatrix:
    """Distributed lower-Cholesky of a symmetric positive-definite
    matrix in the `blocks` layout. Returns L (lower triangular, same
    layout) with A = L @ L.T."""
    if not isinstance(a, ShardedMatrix):
        raise TypeError(
            "paddle.linalg.dist.cholesky expects a ShardedMatrix, "
            f"got {type(a).__name__}")
    if a.layout != "blocks":
        raise ValueError(
            "paddle.linalg.dist.cholesky needs the 'blocks' layout "
            f"(got {a.layout!r})")
    N, N2 = a.shape
    if N != N2:
        raise ValueError(
            f"paddle.linalg.dist.cholesky: matrix must be square, "
            f"got {a.shape}")
    grid_ = a.grid
    nb = _chol_block(N, grid_, block_size)
    label = f"cholesky_{N}_nb{nb}_{a.dtype}"
    compiled = runtime.compile_program(
        label, lambda: _build_cholesky(grid_, N, nb, a.dtype),
        grid_, (a.value,))
    out = runtime.dispatch("factorizations", label, compiled,
                           (a.value,))
    return ShardedMatrix(out, grid_, layout="blocks", _validated=True)


def _build_tsqr(grid_, M, n, dtype):
    p = grid_.nranks
    axes = grid_.all_axes()

    def body(a):
        q1, r1 = jnp.linalg.qr(a, mode="reduced")
        # R-factor reduction: one all_gather tree + a small QR of the
        # stacked (p*n, n) factors, computed redundantly on each rank
        rs = runtime.gather(r1, axes)
        q2, r = jnp.linalg.qr(rs.reshape(p * n, n), mode="reduced")
        rank = runtime.flat_rank(grid_)
        myq2 = lax.dynamic_slice_in_dim(q2, rank * n, n, axis=0)
        q = jnp.matmul(q1, myq2,
                       preferred_element_type=jnp.float32)
        # sign-normalize diag(R) >= 0: the unique factor, directly
        # comparable to any reference modulo its own sign convention
        s = jnp.sign(jnp.diagonal(r))
        s = jnp.where(s == 0, 1, s)
        return ((q * s[None, :]).astype(dtype),
                (r * s[:, None]).astype(dtype))

    from jax.sharding import PartitionSpec as P

    rspec = grid_.row_spec()

    def fn(a):
        return runtime.shard_map(body, grid_.mesh, (rspec,),
                                 (rspec, P(None, None)))(a)

    return fn


def qr(a: ShardedMatrix):
    """Distributed tall-skinny QR (TSQR) of a matrix in the `rows`
    layout. Returns (Q ShardedMatrix in the same layout, R as a
    replicated jax array) with A = Q @ R, Q.T @ Q = I and
    diag(R) >= 0."""
    if not isinstance(a, ShardedMatrix):
        raise TypeError(
            "paddle.linalg.dist.qr expects a ShardedMatrix, got "
            f"{type(a).__name__}")
    if a.layout != "rows":
        raise ValueError(
            "paddle.linalg.dist.qr runs TSQR on the 'rows' layout — "
            f"shard(x, layout='rows') first (got {a.layout!r})")
    M, n = a.shape
    grid_ = a.grid
    if M // grid_.nranks < n:
        raise ValueError(
            "paddle.linalg.dist.qr: TSQR needs each local block row "
            f"at least as tall as wide — {M}x{n} over "
            f"{grid_.nranks} ranks leaves {M // grid_.nranks} rows "
            f"per rank (< {n})")
    label = f"tsqr_{M}x{n}_{a.dtype}"
    compiled = runtime.compile_program(
        label, lambda: _build_tsqr(grid_, M, n, a.dtype),
        grid_, (a.value,))
    q, r = runtime.dispatch("factorizations", label, compiled,
                            (a.value,))
    # r comes back as the documented REPLICATED jax array (P(None,
    # None) out-spec) — no host round-trip here
    return (ShardedMatrix(q, grid_, layout="rows", _validated=True),
            r)


tsqr = qr
