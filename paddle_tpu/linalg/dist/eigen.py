"""Iterative eigensolvers on the distributed matvec/matmul tier.

Both solvers keep the ITERATION VECTORS replicated (they are O(N*k),
tiny next to the O(N^2) operator) and distribute the operator
application — the arxiv 2112.09017 recipe: the matrix never leaves
its 2D block layout, each step is one local block matmul + a psum
along the grid rows + an all_gather along the grid columns.

- `lanczos`: m-step Lanczos with full reorthogonalization; the
  tridiagonal eigenproblem solves on host (it is m x m).
- `eigsh`: blocked subspace iteration + Rayleigh-Ritz for the top-k
  eigenpairs of a symmetric matrix.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import runtime
from .sharded import ShardedMatrix

__all__ = ["matvec", "lanczos", "eigsh"]


def _apply_local(grid_, a, v, cb):
    """One distributed operator application inside a shard_map body:
    v is the full replicated (N,) / (N, k) operand, a the local
    block. Returns the full replicated product."""
    j = lax.axis_index(grid_.cx) if grid_.cx else 0
    vj = lax.dynamic_slice_in_dim(v, j * cb, cb, axis=0)
    w = jnp.matmul(a, vj, preferred_element_type=jnp.float32)
    w = runtime.psum(w, grid_.row_axes())
    w = runtime.gather(w, grid_.col_axes())
    return w.reshape((-1,) + w.shape[2:]).astype(v.dtype)


def _check(a, fname):
    if not isinstance(a, ShardedMatrix):
        raise TypeError(
            f"paddle.linalg.dist.{fname} expects a ShardedMatrix, "
            f"got {type(a).__name__}")
    if a.layout != "blocks":
        raise ValueError(
            f"paddle.linalg.dist.{fname} needs the 'blocks' layout "
            f"(got {a.layout!r})")
    N, N2 = a.shape
    if N != N2:
        raise ValueError(
            f"paddle.linalg.dist.{fname}: matrix must be square, "
            f"got {a.shape}")
    return N


def matvec(a: ShardedMatrix, v):
    """Distributed w = A @ v. `v` is a host/replicated vector (N,) or
    block of vectors (N, k); the result comes back replicated."""
    N = _check(a, "matvec")
    varr = jnp.asarray(
        v._value if hasattr(v, "_value") else v, dtype=a.dtype)
    if varr.shape[0] != N:
        raise ValueError(
            f"paddle.linalg.dist.matvec: operand length "
            f"{varr.shape[0]} != matrix dim {N}")
    grid_ = a.grid
    cb = N // grid_.py
    spec = grid_.block_spec()

    def build():
        from jax.sharding import PartitionSpec as P

        def body(ab, vb):
            return _apply_local(grid_, ab, vb, cb)

        return runtime.shard_map(
            body, grid_.mesh, (spec, P(*([None] * varr.ndim))),
            P(*([None] * varr.ndim)))

    label = f"matvec_{N}x{'x'.join(str(d) for d in varr.shape[1:])}" \
            f"_{a.dtype}"
    compiled = runtime.compile_program(label, build, grid_,
                                      (a.value, varr))
    return runtime.dispatch("matmuls", label, compiled,
                            (a.value, varr))


def lanczos(a: ShardedMatrix, k=1, iters=None, seed=0,
            which="largest"):
    """Approximate the k extreme eigenvalues of a symmetric matrix by
    m-step Lanczos (full reorthogonalization) over the distributed
    matvec. Returns a numpy (k,) array, descending for
    which='largest', ascending for which='smallest'."""
    N = _check(a, "lanczos")
    m = int(iters) if iters else min(N, max(4 * k, 32))
    m = min(m, N)
    if not 0 < k <= m:
        raise ValueError(
            f"paddle.linalg.dist.lanczos: k={k} must be in "
            f"[1, iters={m}]")
    grid_ = a.grid
    cb = N // grid_.py
    spec = grid_.block_spec()
    rng = np.random.default_rng(seed)
    v0 = jnp.asarray(rng.standard_normal(N), dtype=a.dtype)

    def build():
        from jax.sharding import PartitionSpec as P

        def body(ab, v0b):
            v = v0b / jnp.linalg.norm(v0b)
            basis = [v]
            vprev = jnp.zeros_like(v)
            beta_prev = jnp.zeros((), v.dtype)
            alphas, betas = [], []
            for _ in range(m):
                w = _apply_local(grid_, ab, v, cb)
                alpha = jnp.vdot(v, w)
                w = w - alpha * v - beta_prev * vprev
                # full reorthogonalization: replicated O(N*m) work,
                # keeps the tridiagonal honest at f32
                for u in basis:
                    w = w - jnp.vdot(u, w) * u
                beta = jnp.linalg.norm(w)
                alphas.append(alpha)
                betas.append(beta)
                vprev = v
                v = w / jnp.maximum(beta, jnp.asarray(1e-30, w.dtype))
                basis.append(v)
                beta_prev = beta
            # m == 1 has no off-diagonal: stack() rejects empty lists
            offdiag = (jnp.stack(betas[:-1]) if m > 1
                       else jnp.zeros((0,), v.dtype))
            return jnp.stack(alphas), offdiag

        return runtime.shard_map(body, grid_.mesh, (spec, P(None)),
                                 (P(None), P(None)))

    label = f"lanczos_{N}_m{m}_{a.dtype}"
    compiled = runtime.compile_program(label, build, grid_,
                                      (a.value, v0))
    alphas, betas = runtime.dispatch("eigensolves", label, compiled,
                                     (a.value, v0))
    alphas = np.asarray(jax.device_get(alphas), dtype=np.float64)
    betas = np.asarray(jax.device_get(betas), dtype=np.float64)
    tri = np.diag(alphas) + np.diag(betas, 1) + np.diag(betas, -1)
    ritz = np.linalg.eigvalsh(tri)  # ascending
    if which == "largest":
        return ritz[::-1][:k].copy()
    if which == "smallest":
        return ritz[:k].copy()
    raise ValueError(
        f"paddle.linalg.dist.lanczos: which={which!r} must be "
        "'largest' or 'smallest'")


def eigsh(a: ShardedMatrix, k=4, iters=30, seed=0, oversample=4):
    """Top-k eigenpairs of a symmetric matrix by blocked subspace
    iteration + Rayleigh-Ritz over the distributed matmul. Iterates
    an oversampled (k + `oversample`)-column block — the standard
    trick that keeps the k-th pair converging at the gap BEYOND the
    block rather than the (usually tiny) k/k+1 gap. Returns (w, V):
    numpy (k,) eigenvalues descending and (N, k) eigenvectors."""
    N = _check(a, "eigsh")
    if not 0 < k <= N:
        raise ValueError(
            f"paddle.linalg.dist.eigsh: k={k} must be in [1, {N}]")
    kb = min(N, k + max(int(oversample), 0))
    grid_ = a.grid
    cb = N // grid_.py
    spec = grid_.block_spec()
    rng = np.random.default_rng(seed)
    q0 = jnp.asarray(rng.standard_normal((N, kb)), dtype=a.dtype)

    def build():
        from jax.sharding import PartitionSpec as P

        def body(ab, qb):
            q, _ = jnp.linalg.qr(qb, mode="reduced")
            for _ in range(iters):
                y = _apply_local(grid_, ab, q, cb)
                q, _ = jnp.linalg.qr(y, mode="reduced")
            y = _apply_local(grid_, ab, q, cb)
            h = jnp.matmul(q.T, y,
                           preferred_element_type=jnp.float32)
            h = 0.5 * (h + h.T)  # symmetrize roundoff
            w, u = jnp.linalg.eigh(h.astype(q.dtype))
            v = jnp.matmul(q, u,
                           preferred_element_type=jnp.float32)
            return w[::-1], v[:, ::-1].astype(q.dtype)

        return runtime.shard_map(
            body, grid_.mesh, (spec, P(None, None)),
            (P(None), P(None, None)))

    label = f"eigsh_{N}_k{kb}_i{iters}_{a.dtype}"
    compiled = runtime.compile_program(label, build, grid_,
                                      (a.value, q0))
    w, v = runtime.dispatch("eigensolves", label, compiled,
                            (a.value, q0))
    return (np.asarray(jax.device_get(w))[:k],
            np.asarray(jax.device_get(v))[:, :k])
