"""ShardedMatrix — a dense 2D matrix block-distributed over the Fleet
mesh.

The value is ONE global `jax.Array` carrying a NamedSharding whose
PartitionSpec is the block layout (arxiv 2112.09017's checkerboard
distribution expressed through the standard JAX sharding machinery —
no hand-rolled halo bookkeeping):

- `blocks` layout: P(rx, cx) — rank (i, j) owns the (m/px, n/py)
  block A[i, j]. The SUMMA / blocked-factorization layout.
- `rows` layout: P((rx, cx), None) — block rows over the WHOLE grid
  flattened, columns replicated. The tall-skinny (TSQR) layout.

Every spec passes the PTA05x sharding lints before any compile sees
it (structural errors raise immediately with the PTA code in the
message)."""
from __future__ import annotations

import numpy as np
import jax

from ...core.tensor import Tensor
from . import runtime

__all__ = ["ShardedMatrix", "shard"]

LAYOUTS = ("blocks", "rows")


class ShardedMatrix:
    """A global 2D array + its grid + block layout. Construct via
    `shard()` (host/global data) or `from_global()` (an already
    correctly-sharded global array, e.g. an algorithm's output)."""

    def __init__(self, value, grid, layout="blocks", _validated=False):
        if layout not in LAYOUTS:
            raise ValueError(
                f"ShardedMatrix layout must be one of {LAYOUTS}, "
                f"got {layout!r}")
        if value.ndim != 2:
            raise ValueError(
                "ShardedMatrix holds dense 2D matrices — got shape "
                f"{tuple(value.shape)}")
        self._value = value
        self.grid = grid
        self.layout = layout
        if not _validated:
            runtime.lint_spec(self.spec, tuple(value.shape),
                              grid.mesh, name="ShardedMatrix")

    # -- layout ------------------------------------------------------
    @classmethod
    def from_global(cls, value, grid, layout="blocks"):
        """Wrap an already-sharded global jax.Array (e.g. an
        algorithm's output) — the spec still passes the PTA05x
        lints."""
        return cls(value, grid, layout=layout)

    @property
    def spec(self):
        return (self.grid.block_spec() if self.layout == "blocks"
                else self.grid.row_spec())

    @property
    def sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.grid.mesh, self.spec)

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def block_shape(self):
        m, n = self.shape
        if self.layout == "rows":
            return (m // self.grid.nranks, n)
        return (m // self.grid.px, n // self.grid.py)

    # -- data --------------------------------------------------------
    @property
    def value(self):
        """The global jax.Array (sharded)."""
        return self._value

    def gather(self):
        """The full matrix on host, as numpy."""
        return np.asarray(jax.device_get(self._value))

    def to_tensor(self):
        return Tensor(self._value, stop_gradient=True, _internal=True)

    def __repr__(self):
        return (f"ShardedMatrix(shape={self.shape}, "
                f"dtype={self.dtype}, layout={self.layout!r}, "
                f"grid={self.grid})")


def shard(x, mesh=None, row_axis=None, col_axis=None,
          layout="blocks") -> ShardedMatrix:
    """Distribute a (host or global) 2D matrix onto the live Fleet
    mesh in the requested block layout. Indivisible dims fail the
    PTA051 lint in `runtime.lint_spec` (which also covers the
    flattened multi-axis rows layout)."""
    if layout not in LAYOUTS:
        raise ValueError(
            f"paddle.linalg.dist.shard: layout must be one of "
            f"{LAYOUTS}, got {layout!r}")
    g = runtime.grid(mesh, row_axis=row_axis, col_axis=col_axis)
    if isinstance(x, ShardedMatrix):
        x = x._value
    if isinstance(x, Tensor):
        x = x._value
    arr = np.asarray(x) if not isinstance(x, jax.Array) else x
    if arr.ndim != 2:
        raise ValueError(
            "paddle.linalg.dist.shard: expected a 2D matrix, got "
            f"shape {tuple(np.shape(arr))}")
    m, n = int(arr.shape[0]), int(arr.shape[1])
    spec = g.row_spec() if layout == "rows" else g.block_spec()
    runtime.lint_spec(spec, (m, n), g.mesh, name="ShardedMatrix")
    from jax.sharding import NamedSharding

    value = jax.device_put(arr, NamedSharding(g.mesh, spec))
    return ShardedMatrix(value, g, layout=layout, _validated=True)
