"""SUMMA distributed matmul over the Fleet mesh (arxiv 2112.09017).

C = A @ B with A (M,K), B (K,N), C (M,N) all in the `blocks` layout
P(rx, cx) on a px x py grid. The classic panel loop: for each inner
panel of width nb, the grid column owning A's panel broadcasts it
along the rows (mesh axis cx) and the grid row owning B's panel
broadcasts it along the columns (mesh axis rx); every rank then
accumulates one local (M/px, nb) @ (nb, N/py) MXU matmul. Per-rank
comm volume is T * (M/px + N/py) * nb elements of broadcast — priced
by the existing comm/broadcast/{calls,bytes} counters at trace time.

Block size: PADDLE_LINALG_BLOCK pins it; PADDLE_LINALG_AUTOTUNE=1
profiles candidate programs through cost_model.CostModel (whose
compiles ride the persistent compile cache, so a repeated sweep is
warm); otherwise the largest divisor of gcd(K/px, K/py) capped at
DEFAULT_BLOCK_CAP.
"""
from __future__ import annotations

import math
import os

import jax.numpy as jnp
from jax import lax

from . import runtime
from .sharded import ShardedMatrix

__all__ = ["matmul", "choose_block_size", "block_candidates"]

DEFAULT_BLOCK_CAP = 256

# chosen block size per (grid sig, M, K, N, dtype) — one autotune
# sweep per shape family
_chosen: dict = {}

_cost_model = None


def _cost():
    global _cost_model
    if _cost_model is None:
        from ...cost_model import CostModel

        _cost_model = CostModel()
    return _cost_model


def block_candidates(K, grid_, cap=DEFAULT_BLOCK_CAP):
    """Valid SUMMA panel widths: divisors of gcd(K/px, K/py), largest
    first, capped (a panel wider than the cap stops paying off and
    inflates the broadcast working set)."""
    g = runtime.block_divisor(K, grid_.px, grid_.py)
    if g <= 0:
        raise ValueError(
            f"paddle.linalg.dist.matmul: inner dim {K} is not "
            f"divisible by the {grid_.px}x{grid_.py} grid")
    divs = [d for d in range(1, g + 1) if g % d == 0 and d <= cap]
    return sorted(divs, reverse=True)


def _build(grid_, M, K, N, nb, dtype):
    """The traceable SUMMA island for one shape/block choice."""
    px, py = grid_.px, grid_.py
    ka, kb = K // py, K // px  # A / B inner extents per rank

    def body(a, b):
        acc = jnp.zeros((a.shape[0], b.shape[1]), jnp.float32)
        for t in range(K // nb):
            g0 = t * nb
            a_pan = lax.slice_in_dim(a, g0 % ka, g0 % ka + nb, axis=1)
            b_pan = lax.slice_in_dim(b, g0 % kb, g0 % kb + nb, axis=0)
            # owner column of A's panel broadcasts along the row;
            # owner row of B's panel broadcasts along the column
            a_pan = runtime.bcast(a_pan, grid_.row_axes(), g0 // ka)
            b_pan = runtime.bcast(b_pan, grid_.col_axes(), g0 // kb)
            acc = acc + jnp.matmul(
                a_pan, b_pan, preferred_element_type=jnp.float32)
        return acc.astype(dtype)

    spec = grid_.block_spec()

    def fn(a, b):
        return runtime.shard_map(body, grid_.mesh,
                                 (spec, spec), spec)(a, b)

    return fn


def choose_block_size(a: ShardedMatrix, b: ShardedMatrix,
                      candidates=None, max_probes=3):
    """The SUMMA panel width for this (shapes, grid) pairing.

    Precedence: PADDLE_LINALG_BLOCK (validated against the candidate
    set) > cached autotune result > PADDLE_LINALG_AUTOTUNE=1 profile
    sweep over up to `max_probes` candidates via CostModel
    (persistent-cache-warm) > largest capped divisor."""
    grid_ = a.grid
    K = a.shape[1]
    cands = (list(candidates) if candidates
             else block_candidates(K, grid_))
    env = os.environ.get("PADDLE_LINALG_BLOCK")
    if env:
        nb = int(env)
        if nb not in block_candidates(K, grid_, cap=K):
            raise ValueError(
                f"PADDLE_LINALG_BLOCK={nb} does not divide "
                f"gcd(K/px, K/py) for K={K} on {grid_} (valid: "
                f"divisors of "
                f"{runtime.block_divisor(K, grid_.px, grid_.py)})")
        return nb
    key = (grid_.sig(), a.shape, b.shape, str(a.dtype))
    if key in _chosen:
        return _chosen[key]
    if os.environ.get("PADDLE_LINALG_AUTOTUNE", "0") != "1" \
            or len(cands) == 1:
        return cands[0]
    # spread probes across the candidate range (largest, middle,
    # smallest) — adjacent divisors measure within noise of each other
    probes = sorted({cands[0], cands[len(cands) // 2], cands[-1]},
                    reverse=True)[:max_probes]
    M, N = a.shape[0], b.shape[1]
    best, best_t = probes[0], math.inf
    for nb in probes:
        fn = _build(grid_, M, K, N, nb, a.dtype)
        t = _cost().profile_measure(fn, a.value, b.value,
                                    warmup=1, iters=2)
        if t < best_t:
            best, best_t = nb, t
    _chosen[key] = best
    return best


def matmul(a: ShardedMatrix, b: ShardedMatrix,
           block_size=None) -> ShardedMatrix:
    """Distributed C = A @ B (SUMMA). Both operands must share the
    grid and the `blocks` layout; the result lands in the same
    layout."""
    if not isinstance(a, ShardedMatrix) or \
            not isinstance(b, ShardedMatrix):
        raise TypeError(
            "paddle.linalg.dist.matmul expects two ShardedMatrix "
            f"operands, got ({type(a).__name__}, {type(b).__name__})")
    if a.grid.sig() != b.grid.sig():
        raise ValueError(
            "paddle.linalg.dist.matmul: operands live on different "
            f"grids ({a.grid} vs {b.grid})")
    if a.layout != "blocks" or b.layout != "blocks":
        raise ValueError(
            "paddle.linalg.dist.matmul needs the 'blocks' layout "
            f"(got {a.layout!r} @ {b.layout!r})")
    M, K = a.shape
    K2, N = b.shape
    if K != K2:
        raise ValueError(
            f"paddle.linalg.dist.matmul: inner dims differ — "
            f"A {a.shape} @ B {b.shape}")
    grid_ = a.grid
    if N % grid_.py or M % grid_.px or K % grid_.px or K % grid_.py:
        raise ValueError(
            "paddle.linalg.dist.matmul: shapes "
            f"{a.shape} @ {b.shape} do not tile the "
            f"{grid_.px}x{grid_.py} grid")
    nb = int(block_size) if block_size else choose_block_size(a, b)
    if (K // grid_.py) % nb or (K // grid_.px) % nb:
        raise ValueError(
            f"paddle.linalg.dist.matmul: block_size {nb} must divide "
            f"gcd(K/px, K/py) = "
            f"{runtime.block_divisor(K, grid_.px, grid_.py)}")
    label = f"summa_{M}x{K}x{N}_nb{nb}_{a.dtype}"
    compiled = runtime.compile_program(
        label, lambda: _build(grid_, M, K, N, nb, a.dtype),
        grid_, (a.value, b.value))
    out = runtime.dispatch("matmuls", label, compiled,
                           (a.value, b.value))
    return ShardedMatrix(out, grid_, layout="blocks", _validated=True)
