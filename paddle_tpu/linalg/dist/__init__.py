"""paddle.linalg.dist — SUMMA-style distributed linear algebra on the
Fleet mesh (ISSUE 12 / ROADMAP item 4, per PAPERS.md arxiv
2112.09017 "Large Scale Distributed Linear Algebra With TPUs").

Dense matrices live in `ShardedMatrix` block layouts (NamedSharding /
PartitionSpec over the live mesh); algorithms are shard_map islands
compiled through the standard jit + persistent-compile-cache spine,
their collectives routed through `distributed/collective.py` so
comm/<op>/{calls,bytes} telemetry, the flight recorder, the
`linalg_dispatch` chaos site, and the PTA05x sharding lints all apply
exactly as they do to training and serving.

    mesh = paddle.distributed.build_mesh({"dp": 2, "mp": 4})
    paddle.distributed.set_mesh(mesh)
    A = dist.shard(a_host)                    # blocks layout P(dp, mp)
    C = dist.matmul(A, dist.shard(b_host))    # SUMMA
    L = dist.cholesky(dist.shard(spd_host))   # blocked right-looking
    Q, R = dist.qr(dist.shard(tall, layout="rows"))   # TSQR
    w = dist.lanczos(A_sym, k=2)              # extreme eigenvalues
    w, V = dist.eigsh(A_sym, k=8)             # subspace iteration

Env: PADDLE_LINALG_AXES picks the grid axes, PADDLE_LINALG_BLOCK pins
the SUMMA panel width, PADDLE_LINALG_AUTOTUNE=1 profiles panel
candidates through cost_model.CostModel."""
from .sharded import ShardedMatrix, shard
from .summa import matmul, choose_block_size, block_candidates
from .factorizations import cholesky, qr, tsqr
from .eigen import matvec, lanczos, eigsh
from .runtime import Grid, grid, clear_program_cache

__all__ = [
    "ShardedMatrix", "shard", "matmul", "choose_block_size",
    "block_candidates", "cholesky", "qr", "tsqr", "matvec",
    "lanczos", "eigsh", "Grid", "grid", "clear_program_cache",
]
