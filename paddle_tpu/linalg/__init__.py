"""paddle.linalg namespace (reference: python/paddle/linalg.py).

Promoted to a package in ISSUE 12: the single-device op surface
re-exports `ops.linalg` unchanged, and `paddle.linalg.dist` is now
the SUMMA-style DISTRIBUTED tier over the Fleet mesh (ShardedMatrix +
distributed matmul/Cholesky/TSQR/eigensolvers — ROADMAP item 4, per
PAPERS.md arxiv 2112.09017). The p-norm distance op that used to sit
at this name stays available as `paddle.dist` and
`paddle.linalg.pdist_op` (the subpackage deliberately wins the
`linalg.dist` attribute — the ISSUE-12 API contract)."""
# the subpackage must import BEFORE the star re-export: the ops
# surface also exports a `dist` (the p-norm distance op), and
# `from . import dist` after the star would see the attribute already
# bound and silently skip importing the subpackage
from . import dist
from ..ops.linalg import *  # noqa: F401,F403
from ..ops.linalg import __all__ as _OPS_ALL
from ..ops.linalg import dist as pdist_op  # the shadowed distance op

# the distributed subpackage wins the `dist` name (ISSUE 12)
import sys as _sys

dist = _sys.modules[__name__ + ".dist"]

__all__ = list(_OPS_ALL) + ["pdist_op"]
