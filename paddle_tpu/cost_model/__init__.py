"""paddle.cost_model (reference: python/paddle/cost_model/cost_model.py
+ framework/ir/cost_model.cc — per-op time/memory profiling and static
cost estimates used by auto-parallel planning).

TPU-native design: static costs come from XLA itself —
`jit(fn).lower().compile().cost_analysis()` exposes the compiler's
flops/bytes estimates (strictly better than the reference's hand-kept
per-op GFLOP tables) and `memory_analysis()` the HBM byte breakdown;
measured costs time the compiled executable. Compiled executables
cache per (fn, arg shapes/dtypes) so a planner interleaving
static_cost / memory_cost / profile_measure over the same candidate
compiles it ONCE. Works on whole callables or on static-graph
Programs (replayed)."""
from __future__ import annotations

import time
from collections import OrderedDict

import numpy as np
import jax
from jax import tree_util

__all__ = ["CostModel"]

# LRU bounds: the caches strongly pin fn/program AND the compiled XLA
# executable (that's what makes repeat probes free), so a planner
# sweeping hundreds of candidates must not grow them without bound
_CACHE_MAX = 32   # compiled executables, (fn, signature)-keyed
_PROG_MAX = 8     # replay closures, (program, feed-names)-keyed


def _sig_of(args):
    """Shape/dtype signature of an argument pytree — the cache key
    leg that makes one compile serve every same-shaped probe."""
    leaves, treedef = tree_util.tree_flatten(args)
    sig = []
    for v in leaves:
        dt = getattr(v, "dtype", None)
        if dt is None:
            dt = np.asarray(v).dtype
        sig.append((tuple(np.shape(v)), str(dt)))
    return treedef, tuple(sig)


class CostModel:
    def __init__(self):
        # (id(fn), treedef, shapes/dtypes) -> jax.stages.Compiled;
        # fn kept alive alongside so id() can't be recycled. LRU,
        # bounded by _CACHE_MAX.
        self._cache = OrderedDict()
        # (id(program), version, feed names) ->
        # (program, replay fn, params). LRU, bounded by _PROG_MAX.
        self._prog_fns = OrderedDict()

    def _compiled(self, fn, args):
        """The compiled executable for (fn, arg signature) — compiled
        on first use, cached for every later static_cost /
        memory_cost / profile_measure probe of the same candidate.
        With PADDLE_COMPILE_CACHE_DIR set, the compile also consults
        the persistent on-disk cache (jit.persistent_cache), so a
        planner sweep doesn't recompile candidates the fleet (or a
        previous sweep) already built."""
        treedef, sig = _sig_of(args)
        key = (id(fn), treedef, sig)
        ent = self._cache.get(key)
        if ent is None or ent[0] is not fn:
            lowered = jax.jit(fn).lower(*args)
            from ..jit import persistent_cache as _pcache

            if _pcache.enabled():
                label = "cost_model:" + (
                    getattr(fn, "__qualname__", None)
                    or getattr(fn, "__name__", "fn"))
                compiled, _ = _pcache.load_or_compile(lowered, label)
            else:
                compiled = lowered.compile()
            ent = (fn, compiled)
            self._cache[key] = ent
            while len(self._cache) > _CACHE_MAX:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(key)
        return ent[1]

    def _drop_cached_fn(self, fn):
        """Purge `fn`'s compiled executables from _cache — called when
        a replay closure is evicted so its executables go with it."""
        for ck in [k for k, v in self._cache.items() if v[0] is fn]:
            del self._cache[ck]

    # -- static (compiler) costs ------------------------------------------
    def static_cost(self, fn, *example_args):
        """XLA cost analysis: {'flops': ..., 'bytes accessed': ...}."""
        ca = self._compiled(fn, example_args).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return dict(ca or {})

    def memory_cost(self, fn, *example_args):
        """XLA memory analysis of the compiled fn: the
        argument/output/temp/generated-code byte breakdown plus the
        peak-usage total — the per-program HBM footprint capacity
        planning sizes against (monitor/memory.py publishes the same
        numbers, gauge-backed, for live jit programs; a planner
        probing dozens of candidates goes through here so the
        registry isn't spammed)."""
        from ..monitor.memory import extract_memory_analysis

        return extract_memory_analysis(
            self._compiled(fn, example_args)) or {}

    def profile_measure(self, fn, *example_args, warmup=2, iters=10):
        """Measured step time of the compiled fn (reference
        profile_measure): returns seconds/iteration. Shares the
        executable static_cost/memory_cost compiled — no re-jit."""
        jfn = self._compiled(fn, example_args)
        out = None
        for _ in range(warmup):
            out = jfn(*example_args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*example_args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    # -- static-graph programs --------------------------------------------
    def program_cost(self, program, feed):
        """Static cost of a recorded paddle.static Program: replays the
        graph under lower() and returns XLA's analysis plus per-op
        counts (the ir/cost_model.cc shape of answer)."""
        from ..static.graph import replay_block

        feeds = {n: np.asarray(v) for n, v in feed.items()}
        # ONE replay closure per (program, feed names), cached like
        # the compiled executables: a fresh closure per call would
        # mint a fresh id(fn) cache key every time, so repeated
        # probes of the same program could never hit the compile
        # cache and each miss would pin another executable
        # _version leg like the executor cache (static/__init__.py):
        # a pass mutating the program must mint a fresh closure and
        # recompile, not reuse pre-pass costs
        pkey = (id(program), getattr(program, "_version", 0),
                tuple(sorted(feeds)))
        ent = self._prog_fns.get(pkey)
        if ent is None or ent[0] is not program:
            # a version bump mints a fresh pkey (and an id-recycled
            # program a fresh closure), so drop this program's
            # stale-version entries — and any entry a recycled id
            # shadows — along with the compiled executables their
            # closures pinned in _cache: a planner loop alternating
            # probe / mutating pass would otherwise accumulate
            # unreachable-by-key executables forever
            stale = [k for k, v in self._prog_fns.items()
                     if (v[0] is program and k[1] != pkey[1])
                     or k == pkey]
            for k in stale:
                self._drop_cached_fn(self._prog_fns.pop(k)[1])
            feed_vars = {n: program._feeds[n] for n in feeds}
            t_params = program.all_parameters()

            def fn(feed_vals, pvals):
                env = {}
                for n, var in feed_vars.items():
                    env[id(var)] = feed_vals[n]
                for p, v in zip(t_params, pvals):
                    env[id(p)] = v
                replay_block(program.global_block(), env)
                outs = []
                for blk in program.blocks:
                    for op in blk.ops:
                        for v in op.out_vars:
                            if id(v) in env:
                                outs.append(env[id(v)])
                return outs[-1] if outs else 0.0

            ent = (program, fn, t_params)
            self._prog_fns[pkey] = ent
            while len(self._prog_fns) > _PROG_MAX:
                self._drop_cached_fn(
                    self._prog_fns.popitem(last=False)[1][1])
        else:
            self._prog_fns.move_to_end(pkey)
        _, fn, t_params = ent

        pvals = [p._value for p in t_params]
        cost = self.static_cost(fn, feeds, pvals)
        op_histogram = {}
        for blk in program.blocks:
            for op in blk.ops:
                op_histogram[op.type] = op_histogram.get(op.type, 0) + 1
        cost["op_count"] = sum(op_histogram.values())
        cost["op_histogram"] = op_histogram
        return cost
