"""paddle.cost_model (reference: python/paddle/cost_model/cost_model.py
+ framework/ir/cost_model.cc — per-op time/memory profiling and static
cost estimates used by auto-parallel planning).

TPU-native design: static costs come from XLA itself —
`jit(fn).lower().compile().cost_analysis()` exposes the compiler's
flops/bytes estimates (strictly better than the reference's hand-kept
per-op GFLOP tables); measured costs time the compiled executable.
Works on whole callables or on static-graph Programs (replayed)."""
from __future__ import annotations

import time

import numpy as np
import jax

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._cache = {}

    # -- static (compiler) costs ------------------------------------------
    def static_cost(self, fn, *example_args):
        """XLA cost analysis: {'flops': ..., 'bytes accessed': ...}."""
        compiled = jax.jit(fn).lower(*example_args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return dict(ca or {})

    def profile_measure(self, fn, *example_args, warmup=2, iters=10):
        """Measured step time of the jitted fn (reference
        profile_measure): returns seconds/iteration."""
        jfn = jax.jit(fn)
        out = None
        for _ in range(warmup):
            out = jfn(*example_args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*example_args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    # -- static-graph programs --------------------------------------------
    def program_cost(self, program, feed):
        """Static cost of a recorded paddle.static Program: replays the
        graph under lower() and returns XLA's analysis plus per-op
        counts (the ir/cost_model.cc shape of answer)."""
        from ..static.graph import replay_block

        feeds = {n: np.asarray(v) for n, v in feed.items()}
        feed_vars = {n: program._feeds[n] for n in feeds}
        t_params = program.all_parameters()

        def fn(feed_vals, pvals):
            env = {}
            for n, var in feed_vars.items():
                env[id(var)] = feed_vals[n]
            for p, v in zip(t_params, pvals):
                env[id(p)] = v
            replay_block(program.global_block(), env)
            outs = []
            for blk in program.blocks:
                for op in blk.ops:
                    for v in op.out_vars:
                        if id(v) in env:
                            outs.append(env[id(v)])
            return outs[-1] if outs else 0.0

        pvals = [p._value for p in t_params]
        cost = self.static_cost(fn, feeds, pvals)
        op_histogram = {}
        for blk in program.blocks:
            for op in blk.ops:
                op_histogram[op.type] = op_histogram.get(op.type, 0) + 1
        cost["op_count"] = sum(op_histogram.values())
        cost["op_histogram"] = op_histogram
        return cost
