"""Custom autograd via PyLayer (reference:
python/paddle/autograd/py_layer.py, imperative/py_layer_fwd.h).

TPU-native: a PyLayer's forward runs under no_grad; its backward is
spliced into the tape as a synthetic node whose vjp calls the
user-defined backward with Tensors.
"""
from __future__ import annotations

from ..core import engine
from ..core.engine import TapeNode, _state, no_grad
from ..core.tensor import Tensor

import jax.numpy as jnp
from jax import tree_util


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        in_tensors = [a for a in args if isinstance(a, Tensor)]
        requires = engine.is_grad_enabled() and any(
            not t.stop_gradient for t in in_tensors)
        if not requires:
            return outs

        out_avals = [(tuple(o.shape), o._value.dtype) for o in out_list]
        out_treedef = tree_util.tree_structure([0] * len(out_list))

        def vjp_fn(cotangents):
            cots = [Tensor(c, stop_gradient=True, _internal=True)
                    for c in cotangents]
            with no_grad():
                grads = cls.backward(ctx, *cots)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            vals = []
            gi = 0
            for a in args:
                if isinstance(a, Tensor):
                    g = grads[gi] if gi < len(grads) else None
                    gi += 1
                    vals.append(None if g is None else
                                (g._value if isinstance(g, Tensor) else jnp.asarray(g)))
            return tuple(vals)

        _state.seq += 1
        node = TapeNode(_state.seq, f"py_layer_{cls.__name__}", vjp_fn,
                        in_tensors, out_treedef, out_avals)
        wrapped = []
        for i, o in enumerate(out_list):
            t = Tensor(o._value, stop_gradient=False, _internal=True)
            t._node = node
            t._out_index = i
            wrapped.append(t)
        return wrapped[0] if single else tuple(wrapped)


PyLayerBackward = PyLayer
