"""Functional autodiff API (reference: python/paddle/autograd/functional.py).

TPU-native: these delegate to jax.jacobian/jvp/vjp over the pure traced
function, rather than replaying the tape — exact and compiled.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import engine
from ..core.tensor import Tensor


def _fn_on_arrays(func, example_args):
    def f(*arrs):
        with engine.trace_mode():
            targs = [Tensor(a, stop_gradient=False, _internal=True)
                     for a in arrs]
            out = func(*targs)
            if isinstance(out, (list, tuple)):
                return tuple(o._value for o in out)
            return out._value

    return f


def _vals(xs):
    if isinstance(xs, Tensor):
        return (xs._value,), True
    return tuple(x._value for x in xs), False


def jacobian(func, xs, is_batched=False):
    vals, single = _vals(xs)
    f = _fn_on_arrays(func, vals)
    jac = jax.jacobian(f, argnums=tuple(range(len(vals))))(*vals)
    def wrap(j):
        return Tensor(j, stop_gradient=True, _internal=True)

    if single:
        j = jac[0] if isinstance(jac, tuple) else jac
        return wrap(j)
    return jax.tree_util.tree_map(wrap, jac)


def hessian(func, xs, is_batched=False):
    vals, single = _vals(xs)
    f = _fn_on_arrays(func, vals)
    hes = jax.hessian(f, argnums=tuple(range(len(vals))))(*vals)

    def wrap(h):
        return Tensor(h, stop_gradient=True, _internal=True)

    if single:
        h = hes
        while isinstance(h, tuple):
            h = h[0]
        return wrap(h)
    return jax.tree_util.tree_map(wrap, hes)


def vjp(func, xs, v=None):
    vals, single = _vals(xs)
    f = _fn_on_arrays(func, vals)
    out, vjp_fn = jax.vjp(f, *vals)

    def wrap(o):
        return Tensor(o, stop_gradient=True, _internal=True)

    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        vv = v if isinstance(v, (list, tuple)) else [v]
        cot = tuple(t._value for t in vv)
        if not isinstance(out, tuple):
            cot = cot[0]
    grads = vjp_fn(cot)
    outs = jax.tree_util.tree_map(wrap, out)
    gouts = [wrap(g) for g in grads]
    return outs, (gouts[0] if single else gouts)


def jvp(func, xs, v=None):
    vals, single = _vals(xs)
    f = _fn_on_arrays(func, vals)
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        vv = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(t._value for t in vv)
    out, tangent_out = jax.jvp(f, vals, tangents)

    def wrap(o):
        return Tensor(o, stop_gradient=True, _internal=True)

    return (jax.tree_util.tree_map(wrap, out),
            jax.tree_util.tree_map(wrap, tangent_out))
