"""paddle.autograd (reference: python/paddle/autograd/)."""
from ..core.engine import backward as _backward_engine
from ..core.engine import grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled
from .py_layer import PyLayer, PyLayerContext
from .functional import jacobian, hessian, vjp, jvp


def backward(tensors, grad_tensors=None, retain_graph=False):
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        _backward_engine(t, g, retain_graph=retain_graph)
