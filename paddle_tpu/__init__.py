"""paddle_tpu — a TPU-native deep learning framework with the
capability surface of PaddlePaddle (reference snapshot: hnxxd/Paddle
v2.3-dev), built from scratch on JAX/XLA/Pallas.

Top-level namespace mirrors `paddle.*` (reference:
python/paddle/__init__.py): tensor ops, nn, optimizer, io, amp,
distributed, vision, jit, static, metric, distribution.

Architecture (vs the reference):
- dygraph = tape autograd over pure-jax kernels (core/engine.py)
- static graph / jit = jax.jit tracing of the same kernels (jit/)
- kernels = functional jax ops (ops/) — the single PHI-like library
- distributed = jax.sharding Mesh + XLA collectives (distributed/)
"""
from __future__ import annotations

import sys as _sys

from .core import dtype as _dtype_mod
from .core.dtype import (
    bfloat16, float16, float32, float64, int8, int16, int32, int64, uint8,
    bool_ as bool8, complex64, complex128,
)
from .core.place import (
    CPUPlace, TPUPlace, CUDAPinnedPlace, Place, set_device, get_device,
    is_compiled_with_cuda, is_compiled_with_tpu,
)
from .core.tensor import Tensor, to_tensor, Parameter
from .core.engine import no_grad, enable_grad, set_grad_enabled, is_grad_enabled
from .core import engine as _engine
from .core.flags import get_flags, set_flags

from . import ops
from .ops import *  # noqa: F401,F403 — flat paddle.* op surface
from .ops.random import seed, get_rng_state, set_rng_state
from .ops import random as _random_ops

# subpackages (paddle.nn, paddle.optimizer, ...)
from . import nn
from . import optimizer
from . import io
from . import amp
from . import jit
from . import static
from . import metric
from . import distribution
from . import vision
from . import distributed
from . import device
from . import autograd
from . import incubate
from . import inference
from . import profiler
from . import monitor
from . import text
from . import hub
from . import onnx
from . import sparse
from . import quantization
from . import cost_model
from . import analysis
from . import utils
# `from .ops import *` above bound `linalg` to ops.linalg, so a bare
# `from . import linalg` would see the attribute and silently skip
# importing the PACKAGE (pre-ISSUE-12 the two surfaces were
# identical, which hid this). Import through the submodule path and
# rebind explicitly: paddle.linalg is the package, whose .dist is the
# distributed tier.
from .linalg import dist as _linalg_dist  # noqa: F401 — forces the package import
linalg = _sys.modules[__name__ + ".linalg"]
from . import fft
from . import signal
from . import version
from .framework import save, load, set_default_dtype, get_default_dtype
from .hapi import Model, summary, flops
from .jit import to_static

grad = _engine.grad

__version__ = version.full_version


def is_grad_enabled_():
    return _engine.is_grad_enabled()


def disable_static(place=None):
    """Dygraph is the default mode; kept for API parity."""
    return None


def enable_static():
    static._enable_static()


def in_dynamic_mode():
    return not static._static_mode()


def get_device_name(device=None):
    import jax

    return jax.devices()[0].device_kind


def device_count():
    import jax

    return len(jax.devices())


def _register_tensor_methods():
    """Attach the functional op surface as Tensor methods — the analog of
    the generated `core.ops.*` method table (op_function_generator.cc:388).
    """
    import types

    skip = {"to_tensor", "is_tensor", "seed", "zeros", "ones", "full",
            "empty", "arange", "linspace", "logspace", "eye", "meshgrid",
            "rand", "randn", "randint", "randperm", "uniform", "normal",
            "standard_normal", "tril_indices", "triu_indices",
            "broadcast_shape", "one_hot", "einsum"}
    for name, fn in ops.PUBLIC_OPS.items():
        if name in skip or hasattr(Tensor, name):
            continue
        setattr(Tensor, name, fn)

    # dunders
    from .ops import math as m
    from .ops import logic as lg
    from .ops import linalg as la
    from .ops import manipulation as mp

    def _coerce(other, self):
        return other

    Tensor.__add__ = lambda s, o: m.add(s, o)
    Tensor.__radd__ = lambda s, o: m.add(s, o)
    Tensor.__sub__ = lambda s, o: m.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: m.subtract(o, s) if isinstance(o, Tensor) \
        else m.scale(m.subtract(s, o), -1.0)
    Tensor.__mul__ = lambda s, o: m.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: m.multiply(s, o)
    Tensor.__truediv__ = lambda s, o: m.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: m.divide(
        o if isinstance(o, Tensor) else to_tensor(o), s)
    Tensor.__floordiv__ = lambda s, o: m.floor_divide(s, o)
    Tensor.__mod__ = lambda s, o: m.mod(s, o)
    Tensor.__pow__ = lambda s, o: m.pow(s, o)
    Tensor.__rpow__ = lambda s, o: m.pow(to_tensor(o), s)
    Tensor.__neg__ = lambda s: m.neg(s)
    Tensor.__abs__ = lambda s: m.abs(s)
    Tensor.__matmul__ = lambda s, o: la.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: la.matmul(to_tensor(o), s)
    Tensor.__eq__ = lambda s, o: lg.equal(s, o)
    Tensor.__ne__ = lambda s, o: lg.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: lg.less_than(s, o)
    Tensor.__le__ = lambda s, o: lg.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: lg.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: lg.greater_equal(s, o)
    Tensor.__invert__ = lambda s: lg.logical_not(s)
    Tensor.__and__ = lambda s, o: lg.logical_and(s, o) \
        if s.dtype == bool8 else lg.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: lg.logical_or(s, o) \
        if s.dtype == bool8 else lg.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: lg.logical_xor(s, o) \
        if s.dtype == bool8 else lg.bitwise_xor(s, o)

    # in-place-style helpers used by optimizers / init
    def add_(self, y):
        out = m.add(self, y)
        self._value = out._value
        return self

    def subtract_(self, y):
        out = m.subtract(self, y)
        self._value = out._value
        return self

    def multiply_(self, y):
        out = m.multiply(self, y)
        self._value = out._value
        return self

    def scale_(self, scale=1.0, bias=0.0, bias_after_scale=True):
        with no_grad():
            out = m.scale(self.detach(), scale, bias, bias_after_scale)
        self._value = out._value
        return self

    def clip_(self, min=None, max=None):
        with no_grad():
            out = m.clip(self.detach(), min, max)
        self._value = out._value
        return self

    def fill_(self, value):
        import jax.numpy as jnp

        self._value = jnp.full_like(self._value, value)
        return self

    Tensor.add_ = add_
    Tensor.subtract_ = subtract_
    Tensor.multiply_ = multiply_
    Tensor.scale_ = scale_
    Tensor.clip_ = clip_
    Tensor.fill_ = fill_
    Tensor.mean_all = lambda s: m.mean(s)


_register_tensor_methods()

# numpy-free default dtype helpers are in framework.py
