"""paddle.hub (reference: python/paddle/hapi/hub.py). Zero-egress
environment: local-dir loading only; remote sources raise."""
from __future__ import annotations

import importlib.util
import os

__all__ = ["list", "help", "load"]


def _load_entry(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source="local", force_reload=False):
    if source != "local":
        raise RuntimeError("remote hub sources unavailable (no egress)")
    mod = _load_entry(repo_dir)
    return [n for n in dir(mod) if callable(getattr(mod, n))
            and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    mod = _load_entry(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    if source != "local":
        raise RuntimeError("remote hub sources unavailable (no egress)")
    mod = _load_entry(repo_dir)
    return getattr(mod, model)(**kwargs)
