"""paddle.sparse — COO/CSR tensors WITH kernels (r4; r3 shipped
containers only).

Reference surface: paddle/phi/core/sparse_coo_tensor.h,
paddle/phi/kernels/sparse/ (the snapshot carries dense<->COO<->CSR
conversion kernels; the grown library adds matmul / elementwise /
unary / reduction — all provided here, scipy-referenced in
tests/test_sparse.py).

TPU-native design: XLA has no first-class sparse storage, and dynamic
nnz is a dynamic shape — so the representation is STATIC-nnz
coordinate storage and every kernel is a gather/scatter-add program
(ops XLA schedules well on TPU):

  * spmm:   out[rows] += vals * dense[cols]   (gather + segment-add)
  * unary:  zero-preserving fns map over values only
  * binary: pattern-union by concatenation (duplicates are LEGAL in
    COO semantics — to_dense accumulates; `coalesce()` merges
    eagerly, where data-dependent nnz is allowed)
  * CSR kernels reuse the COO programs through a static-shape row
    decompression (searchsorted over crows — nnz is static, so this
    traces under jit)

Gradients: kernels run through apply_op on the VALUES tensors, so the
tape differentiates them like any dense op (gather/scatter-add have
exact VJPs); indices are integer tensors with zero tangents.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.engine import apply_op
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "matmul", "masked_matmul", "add", "subtract",
    "multiply", "divide", "relu", "tanh", "sin", "sinh", "asin",
    "asinh", "atan", "atanh", "sqrt", "square", "abs", "pow", "neg",
    "cast", "scale", "sum", "transpose", "to_sparse_coo",
    "is_same_shape",
]


def _as_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    arr = np.asarray(x)
    if dtype is not None:
        arr = arr.astype(dtype)
    return to_tensor(arr)


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """indices [sparse_ndim, nnz] int32 + values [nnz, *dense_dims].
    Duplicate coordinates are allowed and accumulate (COO semantics);
    coalesce() merges them eagerly."""

    def __init__(self, indices, values, shape):
        self.indices_t = indices
        self.values_t = values
        self.dense_shape = [int(s) for s in shape]

    # -- container API -------------------------------------------------
    def indices(self):
        return self.indices_t

    def values(self):
        return self.values_t

    @property
    def shape(self):
        return self.dense_shape

    @property
    def dtype(self):
        return self.values_t.dtype

    def nnz(self):
        return int(self.indices_t.shape[1])

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_dense(self):
        sparse_nd = int(self.indices_t.shape[0])
        full_shape = tuple(self.dense_shape)

        def _k(idx, vals):
            out = jnp.zeros(full_shape, vals.dtype)
            return out.at[tuple(idx[d] for d in range(sparse_nd))
                          ].add(vals)

        return apply_op("sparse_coo_to_dense", _k, self.indices_t,
                        self.values_t)

    def coalesce(self):
        """Merge duplicate coordinates (eager only: the merged nnz is
        data-dependent, which XLA's static shapes cannot express —
        the same boundary the reference's Coalesce kernel draws)."""
        idx = np.asarray(self.indices_t._value)
        vals = np.asarray(self.values_t._value)
        keys = np.ravel_multi_index(
            tuple(idx), tuple(self.dense_shape[:idx.shape[0]]))
        uniq, inv = np.unique(keys, return_inverse=True)
        merged = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        np.add.at(merged, inv, vals)
        new_idx = np.stack(np.unravel_index(
            uniq, tuple(self.dense_shape[:idx.shape[0]])))
        return SparseCooTensor(
            to_tensor(new_idx.astype(np.int32)), to_tensor(merged),
            self.dense_shape)

    def to_sparse_csr(self):
        if len(self.dense_shape) != 2:
            raise ValueError("to_sparse_csr: 2-D only")
        c = self.coalesce()
        idx = np.asarray(c.indices_t._value)
        nrows = self.dense_shape[0]
        crows = np.zeros(nrows + 1, np.int32)
        np.add.at(crows, idx[0] + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        return SparseCsrTensor(to_tensor(crows),
                               to_tensor(idx[1].astype(np.int32)),
                               c.values_t, self.dense_shape)

    def astype(self, dtype):
        return cast(self, value_dtype=dtype)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.dense_shape}, "
                f"nnz={self.nnz()}, dtype={self.dtype})")

    # operator sugar
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __neg__(self):
        return neg(self)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor:
    """crows [nrows+1], cols [nnz], values [nnz] — 2-D CSR."""

    def __init__(self, crows, cols, values, shape):
        self.crows_t = crows
        self.cols_t = cols
        self.values_t = values
        self.dense_shape = [int(s) for s in shape]

    def crows(self):
        return self.crows_t

    def cols(self):
        return self.cols_t

    def values(self):
        return self.values_t

    @property
    def shape(self):
        return self.dense_shape

    @property
    def dtype(self):
        return self.values_t.dtype

    def nnz(self):
        return int(self.cols_t.shape[0])

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def _rows(self):
        """Static-shape row decompression: rows[i] = the row whose
        [crows[r], crows[r+1]) range contains i. searchsorted keeps
        the [nnz] output shape static, so this traces under jit
        (np.repeat over diff(crows) would not)."""
        nnz = int(self.cols_t.shape[0])

        def _k(crows):
            pos = jnp.arange(nnz, dtype=jnp.int32)
            return (jnp.searchsorted(crows, pos, side="right") - 1
                    ).astype(jnp.int32)

        return apply_op("csr_rows", _k, self.crows_t)

    def to_sparse_coo(self, sparse_dim=2):
        rows = self._rows()

        def _k(rows, cols):
            return jnp.stack([rows, cols.astype(jnp.int32)])

        idx = apply_op("csr_to_coo_indices", _k, rows, self.cols_t)
        return SparseCooTensor(idx, self.values_t, self.dense_shape)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def astype(self, dtype):
        return cast(self, value_dtype=dtype)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.dense_shape}, "
                f"nnz={self.nnz()}, dtype={self.dtype})")

    def __matmul__(self, other):
        return matmul(self, other)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    indices = _as_tensor(indices, np.int32)
    values = _as_tensor(values, dtype)
    if shape is None:
        idx = np.asarray(indices._value)
        shape = (idx.max(axis=1) + 1).tolist()
        shape = shape + list(values.shape[1:])
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    return SparseCsrTensor(_as_tensor(crows, np.int32),
                           _as_tensor(cols, np.int32),
                           _as_tensor(values, dtype), shape)


def to_sparse_coo(x, sparse_dim=None):
    """Dense Tensor -> COO (eager: nnz is data-dependent — the
    reference's DenseToSparseCooKernel draws the same boundary)."""
    arr = np.asarray(_val(x))
    nd = sparse_dim or arr.ndim
    flat = arr.reshape(arr.shape[:nd] + (-1,))
    mask = np.any(flat != 0, axis=-1)
    idx = np.stack(np.nonzero(mask)).astype(np.int32)
    vals = arr[tuple(idx)]
    return SparseCooTensor(to_tensor(idx), to_tensor(vals),
                           list(arr.shape))


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------

def _coo_of(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def matmul(x, y, name=None):
    """sparse [M,K] @ dense [K,N] -> dense [M,N] (COO or CSR lhs).
    The kernel is gather(rows of y at cols) * vals -> scatter-add into
    out rows: both primitives carry exact VJPs, so d(out)/d(values)
    and d(out)/d(y) flow through the tape like any dense op."""
    if not isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("sparse.matmul: lhs must be sparse")
    if len(x.shape) != 2:
        raise ValueError("sparse.matmul: 2-D lhs only")
    xc = _coo_of(x)
    m = x.shape[0]

    def _k(idx, vals, dense):
        rows, cols = idx[0], idx[1]
        contrib = vals[:, None] * dense[cols]        # [nnz, N]
        out = jnp.zeros((m, dense.shape[1]), contrib.dtype)
        return out.at[rows].add(contrib)

    return apply_op("sparse_matmul", _k, xc.indices_t, xc.values_t,
                    y if isinstance(y, Tensor) else _as_tensor(y))


def masked_matmul(x, y, mask, name=None):
    """dense [M,K] @ dense [K,N], sampled at `mask`'s sparsity pattern
    (SDDMM). Returns a sparse tensor carrying mask's indices."""
    mc = _coo_of(mask)

    def _k(idx, a, b):
        rows, cols = idx[0], idx[1]
        return jnp.einsum("nk,nk->n", a[rows], b.T[cols])

    vals = apply_op("sparse_sddmm", _k, mc.indices_t,
                    x if isinstance(x, Tensor) else _as_tensor(x),
                    y if isinstance(y, Tensor) else _as_tensor(y))
    return SparseCooTensor(mc.indices_t, vals, mask.shape)


# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------

def _binary_union(x, y, sign):
    """sp +/- sp by pattern union: concatenate coordinates — COO
    permits duplicates (to_dense accumulates), so this is exact with
    STATIC output nnz = nnz_x + nnz_y. coalesce() afterwards if a
    merged pattern is wanted."""
    if list(x.shape) != list(y.shape):
        raise ValueError("sparse add/subtract: shape mismatch")
    xc, yc = _coo_of(x), _coo_of(y)

    def _kidx(ix, iy):
        return jnp.concatenate([ix, iy], axis=1)

    def _kval(vx, vy):
        return jnp.concatenate([vx, sign * vy], axis=0)

    idx = apply_op("sparse_union_idx", _kidx, xc.indices_t,
                   yc.indices_t)
    vals = apply_op("sparse_union_val", _kval, xc.values_t,
                    yc.values_t)
    return SparseCooTensor(idx, vals, x.shape)


def add(x, y, name=None):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and \
            isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        out = _binary_union(x, y, +1)
        return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
            else out
    if not isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        x, y = y, x  # dense + sparse commutes
    xc = _coo_of(x)
    sparse_nd = int(xc.indices_t.shape[0])

    def _k(idx, vals, dense):
        return dense.at[tuple(idx[d] for d in range(sparse_nd))
                        ].add(vals)

    return apply_op("sparse_add_dense", _k, xc.indices_t, xc.values_t,
                    y if isinstance(y, Tensor) else _as_tensor(y))


def subtract(x, y, name=None):
    sp_x = isinstance(x, (SparseCooTensor, SparseCsrTensor))
    sp_y = isinstance(y, (SparseCooTensor, SparseCsrTensor))
    if sp_x and sp_y:
        out = _binary_union(x, y, -1)
        return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
            else out
    # mixed sparse/dense (r4 advisor: this used to fall into
    # _binary_union and die on .indices_t): express via add with the
    # negated other operand — the result is dense either way
    from ..ops import math as _math

    if sp_x:
        return add(x, _math.scale(
            y if isinstance(y, Tensor) else _as_tensor(y), -1.0))
    return add(scale(y, -1.0), x)


def multiply(x, y, name=None):
    """sp * dense / sp * scalar -> sparse with x's pattern (values
    scaled by the dense entries at the coordinates)."""
    if isinstance(y, (int, float)):
        return scale(x, float(y))
    if not isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        x, y = y, x  # dense * sparse commutes (pattern follows sparse)
        if isinstance(y, (int, float)):  # scalar was the LEFT operand
            return scale(x, float(y))
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        # same-shape product: zeros anywhere kill the entry, so
        # multiplying by the other side's dense form is exact
        y = y.to_dense()
    xc = _coo_of(x)
    sparse_nd = int(xc.indices_t.shape[0])

    def _k(idx, vals, dense):
        return vals * dense[tuple(idx[d] for d in range(sparse_nd))]

    vals = apply_op("sparse_mul_dense", _k, xc.indices_t, xc.values_t,
                    y if isinstance(y, Tensor) else _as_tensor(y))
    out = SparseCooTensor(xc.indices_t, vals, x.shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
        else out


def divide(x, y, name=None):
    if isinstance(y, (int, float)):
        return scale(x, 1.0 / float(y))
    if not isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError(
            "sparse.divide: the dividend must be sparse — dense / "
            "sparse would divide by the sparse operand's implicit "
            "zeros almost everywhere; densify explicitly (y.to_dense())"
            " if that is really intended")
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        y = y.to_dense()
    xc = _coo_of(x)
    sparse_nd = int(xc.indices_t.shape[0])

    def _k(idx, vals, dense):
        return vals / dense[tuple(idx[d] for d in range(sparse_nd))]

    vals = apply_op("sparse_div_dense", _k, xc.indices_t, xc.values_t,
                    y if isinstance(y, Tensor) else _as_tensor(y))
    out = SparseCooTensor(xc.indices_t, vals, x.shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
        else out


# ---------------------------------------------------------------------------
# zero-preserving unary ops — map over values, pattern unchanged
# ---------------------------------------------------------------------------

def _unary(name, fn, x):
    def _k(vals):
        return fn(vals)

    vals = apply_op(f"sparse_{name}", _k, x.values_t)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows_t, x.cols_t, vals, x.shape)
    return SparseCooTensor(x.indices_t, vals, x.shape)


def relu(x, name=None):
    return _unary("relu", lambda v: jnp.maximum(v, 0), x)


def tanh(x, name=None):
    return _unary("tanh", jnp.tanh, x)


def sin(x, name=None):
    return _unary("sin", jnp.sin, x)


def sinh(x, name=None):
    return _unary("sinh", jnp.sinh, x)


def asin(x, name=None):
    return _unary("asin", jnp.arcsin, x)


def asinh(x, name=None):
    return _unary("asinh", jnp.arcsinh, x)


def atan(x, name=None):
    return _unary("atan", jnp.arctan, x)


def atanh(x, name=None):
    return _unary("atanh", jnp.arctanh, x)


def sqrt(x, name=None):
    return _unary("sqrt", jnp.sqrt, x)


def square(x, name=None):
    return _unary("square", jnp.square, x)


def abs(x, name=None):  # noqa: A001 - reference name
    return _unary("abs", jnp.abs, x)


def neg(x, name=None):
    return _unary("neg", jnp.negative, x)


def pow(x, factor, name=None):  # noqa: A001 - reference name
    return _unary("pow", lambda v: jnp.power(v, factor), x)


def scale(x, scale_v, bias=0.0, bias_after_scale=True, name=None):
    if bias != 0.0:
        raise ValueError(
            "sparse.scale with bias != 0 densifies (the bias lands on "
            "every zero) — add the bias to to_dense() instead")
    return _unary("scale", lambda v: v * scale_v, x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    def _k(vals):
        return vals.astype(value_dtype) if value_dtype else vals

    vals = apply_op("sparse_cast", _k, x.values_t)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows_t, x.cols_t, vals, x.shape)
    idx = x.indices_t
    if index_dtype is not None:
        idx = to_tensor(np.asarray(idx._value).astype(index_dtype))
    return SparseCooTensor(idx, vals, x.shape)


# ---------------------------------------------------------------------------
# reduction / layout
# ---------------------------------------------------------------------------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Reduce to a DENSE tensor (sum over all, or segment-sum over an
    axis). Hybrid COO (sparse_ndim < tensor rank): sparse axes reduce
    by segment-sum, dense (trailing) axes by reducing the values."""
    xc = _coo_of(x)
    nd = len(x.shape)
    sparse_nd = int(xc.indices_t.shape[0])

    if axis is None:
        def _k(vals):
            out = jnp.sum(vals)
            return out.astype(dtype) if dtype else out

        return apply_op("sparse_sum_all", _k, xc.values_t)
    ax = axis if axis >= 0 else axis + nd
    out_shape = tuple(s for i, s in enumerate(x.shape) if i != ax)

    def _k(idx, vals):
        if ax >= sparse_nd:
            # dense-dim reduction: values axis (ax - sparse_nd) + 1
            red = jnp.sum(vals, axis=ax - sparse_nd + 1)
            out = jnp.zeros(out_shape, red.dtype)
            out = out.at[tuple(idx[d] for d in range(sparse_nd))
                         ].add(red)
        else:
            keep = [idx[d] for d in range(sparse_nd) if d != ax]
            if keep:
                out = jnp.zeros(out_shape, vals.dtype)
                out = out.at[tuple(keep)].add(vals)
            else:
                # the only sparse axis reduced: nothing left to
                # scatter by — the result is the plain value sum
                out = jnp.sum(vals, axis=0)
        if dtype:
            out = out.astype(dtype)
        if keepdim:
            return jnp.expand_dims(out, ax)
        return out

    return apply_op("sparse_sum_axis", _k, xc.indices_t, xc.values_t)


def transpose(x, perm=None, name=None):
    xc = _coo_of(x)
    nd = len(x.shape)
    sparse_nd = int(xc.indices_t.shape[0])
    perm = list(perm) if perm is not None else list(range(nd))[::-1]
    if sparse_nd < nd and sorted(perm[:sparse_nd]) != list(
            range(sparse_nd)):
        raise NotImplementedError(
            "sparse.transpose on a hybrid COO tensor may only permute "
            "within the sparse dims (values carry the dense dims)")

    def _k(idx):
        return jnp.stack([idx[p] for p in perm[:sparse_nd]])

    idx = apply_op("sparse_transpose_idx", _k, xc.indices_t)
    vals = xc.values_t
    if sparse_nd < nd:
        dense_perm = [p - sparse_nd + 1 for p in perm[sparse_nd:]]
        if dense_perm != list(range(1, nd - sparse_nd + 1)):
            def _kv(v):
                return jnp.transpose(v, [0] + dense_perm)

            vals = apply_op("sparse_transpose_vals", _kv, vals)
    out = SparseCooTensor(idx, vals, [x.shape[p] for p in perm])
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
        else out
