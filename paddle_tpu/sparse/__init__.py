"""paddle.sparse (reference: paddle/phi/core/sparse_coo_tensor.h,
python/paddle/sparse). Round-1: COO/CSR containers + conversions +
basic ops; TPU kernels operate on densified segments (XLA has no
first-class sparse)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices_t = indices
        self.values_t = values
        self.dense_shape = list(shape)

    def indices(self):
        return self.indices_t

    def values(self):
        return self.values_t

    @property
    def shape(self):
        return self.dense_shape

    def to_dense(self):
        idx = np.asarray(self.indices_t._value)
        vals = self.values_t._value
        out = jnp.zeros(tuple(self.dense_shape), vals.dtype)
        out = out.at[tuple(idx)].add(vals)
        return Tensor(out, _internal=True)

    def is_sparse(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if not isinstance(indices, Tensor):
        indices = to_tensor(np.asarray(indices))
    if not isinstance(values, Tensor):
        values = to_tensor(np.asarray(values))
    if shape is None:
        idx = np.asarray(indices._value)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows_t = crows
        self.cols_t = cols
        self.values_t = values
        self.dense_shape = list(shape)

    def to_dense(self):
        crows = np.asarray(self.crows_t._value)
        cols = np.asarray(self.cols_t._value)
        vals = self.values_t._value
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        out = jnp.zeros(tuple(self.dense_shape), vals.dtype)
        out = out.at[rows, cols].add(vals)
        return Tensor(out, _internal=True)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    def conv(x):
        return x if isinstance(x, Tensor) else to_tensor(np.asarray(x))

    return SparseCsrTensor(conv(crows), conv(cols), conv(values), shape)
