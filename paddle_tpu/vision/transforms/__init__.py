"""paddle.vision.transforms (reference:
python/paddle/vision/transforms/). numpy/CHW-based implementations."""
from __future__ import annotations

import numbers
import random as _pyrandom

import numpy as np

from ...core.tensor import Tensor, to_tensor as _to_tensor

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Normalize", "Transpose",
    "Resize", "RandomCrop", "CenterCrop", "RandomHorizontalFlip",
    "RandomVerticalFlip", "RandomResizedCrop", "Pad", "BrightnessTransform",
    "ContrastTransform", "SaturationTransform", "HueTransform",
    "ColorJitter", "Grayscale", "RandomRotation", "to_tensor", "normalize",
    "resize", "hflip", "vflip", "crop", "center_crop", "pad",
]


def _img_array(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


def _hwc(img):
    arr = _img_array(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, *inputs):
        if len(inputs) == 1:
            return self._apply_image(inputs[0])
        return tuple(self._apply_image(i) for i in inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, *data):
        for t in self.transforms:
            data = t(*data) if isinstance(data, tuple) else t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False, keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _hwc(img)
        return arr.transpose(self.order)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _hwc(img)
        if self.padding:
            arr = pad(arr, self.padding)
        h, w = arr.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return arr
        top = _pyrandom.randint(0, max(h - th, 0))
        left = _pyrandom.randint(0, max(w - tw, 0))
        return arr[top:top + th, left:left + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if _pyrandom.random() < self.prob:
            return hflip(img)
        return _hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if _pyrandom.random() < self.prob:
            return vflip(img)
        return _hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * _pyrandom.uniform(*self.scale)
            ar = _pyrandom.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                top = _pyrandom.randint(0, h - th)
                left = _pyrandom.randint(0, w - tw)
                cropped = arr[top:top + th, left:left + tw]
                return resize(cropped, self.size, self.interpolation)
        return resize(center_crop(arr, (min(h, w), min(h, w))), self.size,
                      self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _hwc(img).astype(np.float32)
        f = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * f, 0, 255).astype(_hwc(img).dtype)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _hwc(img).astype(np.float32)
        f = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * f + mean, 0, 255).astype(
            _hwc(img).dtype)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _hwc(img).astype(np.float32)
        f = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = arr.mean(axis=2, keepdims=True)
        return np.clip(gray + (arr - gray) * f, 0, 255).astype(
            _hwc(img).dtype)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        return _hwc(img)  # full HSV hue shift: planned


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))

    def _apply_image(self, img):
        out = img
        for t in self.ts:
            out = t._apply_image(out)
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        arr = _hwc(img).astype(np.float32)
        gray = (arr[..., :3] @ np.asarray([0.299, 0.587, 0.114],
                                          np.float32))[..., None]
        if self.n == 3:
            gray = np.repeat(gray, 3, axis=2)
        return gray.astype(_hwc(img).dtype)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def _apply_image(self, img):
        arr = _hwc(img)
        angle = _pyrandom.uniform(*self.degrees)
        k = int(round(angle / 90.0)) % 4
        return np.rot90(arr, k).copy()  # coarse (90° steps); scipy-free


# functional variants ----------------------------------------------------


def to_tensor(img, data_format="CHW"):
    arr = _hwc(img).astype(np.float32)
    if arr.dtype == np.uint8 or arr.max() > 2.0:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return _to_tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _img_array(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    out = (arr - mean.reshape(shape)) / std.reshape(shape)
    if isinstance(img, Tensor):
        return _to_tensor(out)
    return out


def resize(img, size, interpolation="bilinear"):
    arr = _hwc(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    th, tw = size
    import jax
    import jax.numpy as jnp

    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic", "linear": "linear"}.get(interpolation,
                                                          "linear")
    out = jax.image.resize(jnp.asarray(arr.astype(np.float32)),
                           (th, tw, arr.shape[2]), method=method)
    return np.asarray(out).astype(arr.dtype)


def hflip(img):
    return _hwc(img)[:, ::-1].copy()


def vflip(img):
    return _hwc(img)[::-1].copy()


def crop(img, top, left, height, width):
    return _hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return arr[top:top + th, left:left + tw]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _hwc(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    width = ((top, bottom), (left, right), (0, 0))
    if padding_mode == "constant":
        return np.pad(arr, width, mode="constant", constant_values=fill)
    mode = {"replicate": "edge", "reflect": "reflect",
            "circular": "wrap"}.get(padding_mode, padding_mode)
    return np.pad(arr, width, mode=mode)
