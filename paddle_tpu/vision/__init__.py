"""paddle.vision (reference: python/paddle/vision/)."""
from . import models
from . import transforms
from . import datasets
from . import ops
from .models import *  # noqa: F401,F403


def set_image_backend(backend):
    return None


def get_image_backend():
    return "numpy"


def image_load(path, backend=None):
    import numpy as np

    try:
        from PIL import Image

        return Image.open(path)
    except ImportError:
        raise RuntimeError("PIL unavailable")
