"""paddle.vision.ops — detection operator suite.

Parity targets: python/paddle/vision/ops.py (roi_align:1145,
roi_pool:1022, psroi_pool:911, yolo_box:252, deform_conv2d:423) and
paddle/fluid/operators/detection/ (prior_box_op.h, box_coder_op.h,
iou_similarity_op.h, yolo_box_op.h).

TPU-native design notes:
- Everything except `nms` is a pure, static-shaped jax kernel
  (differentiable where the reference op has a grad kernel). roi_pool /
  psroi_pool use a MASK formulation — bin membership is computed by
  comparison against box coordinates, so data-dependent integer bin
  extents never become data-dependent shapes (the XLA constraint the
  reference's per-roi loops don't have).
- roi_align with sampling_ratio <= 0 (adaptive grid count per roi)
  requires a data-dependent number of sample points; under XLA that
  is a dynamic shape, so it raises with guidance to pass an explicit
  ratio (dead-corner-raises rule) rather than silently approximating.
- nms produces a data-dependent-length index list: host/numpy, eager
  only — matching the reference's CPU kernel role in the pipeline.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.engine import apply_op
from ..core.tensor import Tensor, to_tensor
from ..nn import Layer
from ..nn.initializer import Constant

__all__ = [
    "nms", "box_coder", "iou_similarity", "prior_box", "yolo_box",
    "roi_align", "roi_pool", "psroi_pool", "deform_conv2d",
    "RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D",
    "distribute_fpn_proposals",
]


def _pair(v):
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _roi_batch_index(boxes_num, num_rois):
    """[R] batch index per roi from per-image counts (static R)."""
    ends = jnp.cumsum(boxes_num)
    r = jnp.arange(num_rois)
    return jnp.sum(r[:, None] >= ends[None, :], axis=1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# roi_align
# ---------------------------------------------------------------------------

def _k_roi_align(x, boxes, boxes_num, ph, pw, scale, ratio, aligned):
    n, c, h, w = x.shape
    num_rois = boxes.shape[0]
    batch_idx = _roi_batch_index(boxes_num, num_rois)
    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * scale - off
    y1 = boxes[:, 1] * scale - off
    x2 = boxes[:, 2] * scale - off
    y2 = boxes[:, 3] * scale - off
    roi_w = x2 - x1
    roi_h = y2 - y1
    if not aligned:
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph

    gy = jnp.arange(ratio, dtype=x.dtype)
    gx = jnp.arange(ratio, dtype=x.dtype)
    # sample centers: y1 + (i + (iy+0.5)/ratio) * bin_h (reference
    # roi_align_op.h get_indexes_and_ratios)
    iy = jnp.arange(ph, dtype=x.dtype)
    ix = jnp.arange(pw, dtype=x.dtype)
    # [R, ph, ratio]
    sy = (y1[:, None, None] + (iy[None, :, None]
                               + (gy[None, None, :] + 0.5) / ratio)
          * bin_h[:, None, None])
    sx = (x1[:, None, None] + (ix[None, :, None]
                               + (gx[None, None, :] + 0.5) / ratio)
          * bin_w[:, None, None])

    def bilinear(img, yy, xx):
        """img [C,H,W]; yy/xx flat sample coords -> [C, S].

        Border handling per reference roi_align_op.h: coords are
        clamped into [0, size-1] BEFORE floor (a sample at -0.3 reads
        row 0 with weight 1, not rows {-1, 0}), and samples outside
        [-1, size] contribute 0."""
        valid = ((yy >= -1.0) & (yy <= h) & (xx >= -1.0) & (xx <= w))
        yc = jnp.clip(yy, 0.0, h - 1.0)
        xc = jnp.clip(xx, 0.0, w - 1.0)
        y0 = jnp.floor(yc)
        x0 = jnp.floor(xc)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        y1i = jnp.clip(y0i + 1, 0, h - 1)
        x1i = jnp.clip(x0i + 1, 0, w - 1)
        ly = yc - y0
        lx = xc - x0
        v00 = img[:, y0i, x0i]
        v01 = img[:, y0i, x1i]
        v10 = img[:, y1i, x0i]
        v11 = img[:, y1i, x1i]
        out = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
               + v10 * ly * (1 - lx) + v11 * ly * lx)
        return jnp.where(valid[None, :], out, 0.0)

    def per_roi(b, sy_r, sx_r):
        img = x[b]
        # [ph, ratio] x [pw, ratio] grid -> flat samples
        yy = jnp.broadcast_to(sy_r[:, None, :, None],
                              (ph, pw, ratio, ratio)).reshape(-1)
        xx = jnp.broadcast_to(sx_r[None, :, None, :],
                              (ph, pw, ratio, ratio)).reshape(-1)
        vals = bilinear(img, yy, xx)  # [C, ph*pw*ratio*ratio]
        vals = vals.reshape(c, ph, pw, ratio * ratio)
        return jnp.mean(vals, axis=-1)

    return jax.vmap(per_roi)(batch_idx, sy, sx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference vision/ops.py:1145, roi_align_op.h)."""
    ph, pw = _pair(output_size)
    if sampling_ratio <= 0:
        raise NotImplementedError(
            "roi_align: sampling_ratio <= 0 (adaptive per-roi grid) needs "
            "a data-dependent sample count, which XLA's static shapes "
            "cannot express — pass an explicit sampling_ratio (2 matches "
            "the common detector configuration)")
    return apply_op("roi_align", _k_roi_align, x, boxes, boxes_num,
                    ph=ph, pw=pw, scale=float(spatial_scale),
                    ratio=int(sampling_ratio), aligned=bool(aligned))


# ---------------------------------------------------------------------------
# roi_pool / psroi_pool (mask formulation — exact integer-bin semantics
# with static shapes)
# ---------------------------------------------------------------------------

def _k_roi_pool(x, boxes, boxes_num, ph, pw, scale):
    n, c, h, w = x.shape
    num_rois = boxes.shape[0]
    batch_idx = _roi_batch_index(boxes_num, num_rois)
    x1 = jnp.round(boxes[:, 0] * scale)
    y1 = jnp.round(boxes[:, 1] * scale)
    x2 = jnp.round(boxes[:, 2] * scale)
    y2 = jnp.round(boxes[:, 3] * scale)
    roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
    roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
    bin_h = roi_h / ph
    bin_w = roi_w / pw

    hs = jnp.arange(h, dtype=x.dtype)
    ws = jnp.arange(w, dtype=x.dtype)
    i = jnp.arange(ph, dtype=x.dtype)
    j = jnp.arange(pw, dtype=x.dtype)
    # reference roi_pool_op.h: hstart = floor(i*bin_h)+y1 clipped,
    # hend = ceil((i+1)*bin_h)+y1
    hstart = jnp.clip(jnp.floor(i[None, :] * bin_h[:, None])
                      + y1[:, None], 0, h)
    hend = jnp.clip(jnp.ceil((i[None, :] + 1) * bin_h[:, None])
                    + y1[:, None], 0, h)
    wstart = jnp.clip(jnp.floor(j[None, :] * bin_w[:, None])
                      + x1[:, None], 0, w)
    wend = jnp.clip(jnp.ceil((j[None, :] + 1) * bin_w[:, None])
                    + x1[:, None], 0, w)
    # membership masks [R, ph, H], [R, pw, W]
    hm = ((hs[None, None, :] >= hstart[:, :, None])
          & (hs[None, None, :] < hend[:, :, None]))
    wm = ((ws[None, None, :] >= wstart[:, :, None])
          & (ws[None, None, :] < wend[:, :, None]))
    # empty bins (clipped away) output 0 (reference is_empty)
    empty = (hend <= hstart)[:, :, None] | (wend <= wstart)[:, None, :]

    def per_roi(b, hm_r, wm_r, empty_r):
        img = x[b]  # [C, H, W]
        neg = jnp.asarray(-jnp.inf, x.dtype)
        # separable masked max — O(pw*C*H) peak instead of the naive
        # O(ph*pw*C*H*W) joint mask: max over W per column bin first,
        # then over H per row bin
        mw = jnp.where(wm_r[:, None, None, :], img[None], neg)
        colmax = jnp.max(mw, axis=3)  # [pw, C, H]
        mh = jnp.where(hm_r[:, None, None, :], colmax[None], neg)
        out = jnp.max(mh, axis=3)  # [ph, pw, C]
        out = jnp.where(empty_r[..., None], jnp.asarray(0, x.dtype), out)
        return jnp.moveaxis(out, -1, 0)  # [C, ph, pw]

    return jax.vmap(per_roi)(batch_idx, hm, wm, empty)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """RoIPool (reference vision/ops.py:1022, roi_pool_op.h): max pool
    over integer bins; empty bins output 0."""
    ph, pw = _pair(output_size)
    return apply_op("roi_pool", _k_roi_pool, x, boxes, boxes_num,
                    ph=ph, pw=pw, scale=float(spatial_scale))


def _k_psroi_pool(x, boxes, boxes_num, ph, pw, scale, out_c):
    n, c, h, w = x.shape
    num_rois = boxes.shape[0]
    batch_idx = _roi_batch_index(boxes_num, num_rois)
    # reference psroi_pool_op.h: round to integer grid then avg-pool
    # the position-sensitive channel slice
    x1 = jnp.round(boxes[:, 0]) * scale
    y1 = jnp.round(boxes[:, 1]) * scale
    x2 = jnp.round(boxes[:, 2] + 1.0) * scale
    y2 = jnp.round(boxes[:, 3] + 1.0) * scale
    roi_h = jnp.maximum(y2 - y1, 0.1)
    roi_w = jnp.maximum(x2 - x1, 0.1)
    bin_h = roi_h / ph
    bin_w = roi_w / pw

    hs = jnp.arange(h, dtype=x.dtype)
    ws = jnp.arange(w, dtype=x.dtype)
    i = jnp.arange(ph, dtype=x.dtype)
    j = jnp.arange(pw, dtype=x.dtype)
    hstart = jnp.clip(jnp.floor(i[None, :] * bin_h[:, None] + y1[:, None]),
                      0, h)
    hend = jnp.clip(jnp.ceil((i[None, :] + 1) * bin_h[:, None]
                             + y1[:, None]), 0, h)
    wstart = jnp.clip(jnp.floor(j[None, :] * bin_w[:, None] + x1[:, None]),
                      0, w)
    wend = jnp.clip(jnp.ceil((j[None, :] + 1) * bin_w[:, None]
                             + x1[:, None]), 0, w)
    hm = ((hs[None, None, :] >= hstart[:, :, None])
          & (hs[None, None, :] < hend[:, :, None])).astype(x.dtype)
    wm = ((ws[None, None, :] >= wstart[:, :, None])
          & (ws[None, None, :] < wend[:, :, None])).astype(x.dtype)
    cnt = (jnp.einsum("rih,rjw->rij", hm, wm))
    # x reshaped so channel = out_c * (ph*pw): slice (i,j) uses channel
    # block c_out*ph*pw ordering [out_c, ph, pw]
    xr = x.reshape(n, out_c, ph, pw, h, w)

    def per_roi(b, hm_r, wm_r, cnt_r):
        img = xr[b]  # [out_c, ph, pw, H, W]
        s = jnp.einsum("oijhw,ih,jw->oij", img, hm_r, wm_r)
        return s / jnp.maximum(cnt_r[None], 1e-10)

    return jax.vmap(per_roi)(batch_idx, hm, wm, cnt)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """PSRoIPool (reference vision/ops.py:911, psroi_pool_op.h):
    position-sensitive average pooling — input channels C must equal
    out_channels * pooled_h * pooled_w."""
    ph, pw = _pair(output_size)
    c = x.shape[1]
    if c % (ph * pw) != 0:
        raise ValueError(
            f"psroi_pool: input channels {c} must be divisible by "
            f"output_size^2 {ph * pw}")
    return apply_op("psroi_pool", _k_psroi_pool, x, boxes, boxes_num,
                    ph=ph, pw=pw, scale=float(spatial_scale),
                    out_c=c // (ph * pw))


# ---------------------------------------------------------------------------
# yolo_box
# ---------------------------------------------------------------------------

def _k_yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample,
                clip_bbox, scale_x_y, iou_aware, iou_aware_factor):
    n, c, h, w = x.shape
    an_num = len(anchors) // 2
    bias = -0.5 * (scale_x_y - 1.0)
    if iou_aware:
        ious = x[:, :an_num].reshape(n, an_num, 1, h, w)
        px = x[:, an_num:].reshape(n, an_num, 5 + class_num, h, w)
    else:
        px = x.reshape(n, an_num, 5 + class_num, h, w)
    anchors_a = jnp.asarray(anchors, x.dtype).reshape(an_num, 2)
    img_h = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    grid_x = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    sig = jax.nn.sigmoid
    cx = (grid_x + sig(px[:, :, 0]) * scale_x_y + bias) * img_w / w
    cy = (grid_y + sig(px[:, :, 1]) * scale_x_y + bias) * img_h / h
    bw = (jnp.exp(px[:, :, 2]) * anchors_a[None, :, 0, None, None]
          * img_w / (downsample * w))
    bh = (jnp.exp(px[:, :, 3]) * anchors_a[None, :, 1, None, None]
          * img_h / (downsample * h))
    conf = sig(px[:, :, 4])
    if iou_aware:
        iou = sig(ious[:, :, 0])
        conf = (conf ** (1.0 - iou_aware_factor)) * (
            iou ** iou_aware_factor)
    keep = conf >= conf_thresh
    x1 = cx - bw / 2
    y1 = cy - bh / 2
    x2 = cx + bw / 2
    y2 = cy + bh / 2
    if clip_bbox:
        x1 = jnp.maximum(x1, 0.0)
        y1 = jnp.maximum(y1, 0.0)
        x2 = jnp.minimum(x2, img_w - 1)
        y2 = jnp.minimum(y2, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=2)  # [N, an, 4, H, W]
    boxes = jnp.where(keep[:, :, None], boxes, 0.0)
    scores = conf[:, :, None] * sig(px[:, :, 5:])
    scores = jnp.where(keep[:, :, None], scores, 0.0)
    # layout [N, an*H*W, ...] with an-major then hw (reference box_idx =
    # (i*box_num + j*stride + k*w + l))
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, an_num * h * w, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(
        n, an_num * h * w, class_num)
    return boxes, scores


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None,
             scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """YOLOv3 box decoder (reference vision/ops.py:252,
    yolo_box_op.h GetYoloBox). Returns (boxes [N,B,4], scores
    [N,B,class_num]); predictions under conf_thresh are zeroed."""
    return apply_op(
        "yolo_box", _k_yolo_box, x, img_size,
        anchors=tuple(int(a) for a in anchors), class_num=int(class_num),
        conf_thresh=float(conf_thresh), downsample=int(downsample_ratio),
        clip_bbox=bool(clip_bbox), scale_x_y=float(scale_x_y),
        iou_aware=bool(iou_aware),
        iou_aware_factor=float(iou_aware_factor))


# ---------------------------------------------------------------------------
# prior_box / box_coder / iou_similarity
# ---------------------------------------------------------------------------

def _expand_aspect_ratios(aspect_ratios, flip):
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) boxes (reference detection.py:1771,
    prior_box_op.h). Returns (boxes [H,W,num_priors,4], variances same
    shape)."""
    min_sizes = [float(s) for s in (min_sizes if isinstance(
        min_sizes, (list, tuple)) else [min_sizes])]
    max_sizes = [float(s) for s in (max_sizes or [])]
    ars = _expand_aspect_ratios(list(aspect_ratios), flip)
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    def _k(_x):
        cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
        cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
        whs = []  # ordered (w, h) per prior
        for s, mn in enumerate(min_sizes):
            if min_max_aspect_ratios_order:
                whs.append((mn / 2.0, mn / 2.0))
                if max_sizes:
                    m = math.sqrt(mn * max_sizes[s]) / 2.0
                    whs.append((m, m))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    whs.append((mn * math.sqrt(ar) / 2.0,
                                mn / math.sqrt(ar) / 2.0))
            else:
                for ar in ars:
                    whs.append((mn * math.sqrt(ar) / 2.0,
                                mn / math.sqrt(ar) / 2.0))
                if max_sizes:
                    m = math.sqrt(mn * max_sizes[s]) / 2.0
                    whs.append((m, m))
        wh = jnp.asarray(whs, jnp.float32)  # [P, 2]
        ctr = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)
        # boxes [H, W, P, 4] normalized by image size
        b = jnp.stack([
            (ctr[..., 1:2] - wh[None, None, :, 0]) / iw,
            (ctr[..., 0:1] - wh[None, None, :, 1]) / ih,
            (ctr[..., 1:2] + wh[None, None, :, 0]) / iw,
            (ctr[..., 0:1] + wh[None, None, :, 1]) / ih,
        ], axis=-1)
        if clip:
            b = jnp.clip(b, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               b.shape)
        return b, var

    return apply_op("prior_box", _k, input)


def _k_box_coder(prior, pvar, target, code_type, normalized, axis,
                 variance):
    norm = 0.0 if normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + norm
    ph_ = prior[:, 3] - prior[:, 1] + norm
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph_ / 2
    if code_type == "encode_center_size":
        # target [R,4] x prior [C,4] -> [R, C, 4]
        tw = target[:, 2] - target[:, 0] + norm
        th = target[:, 3] - target[:, 1] + norm
        tcx = (target[:, 2] + target[:, 0]) / 2
        tcy = (target[:, 3] + target[:, 1]) / 2
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph_[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph_[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if pvar is not None:
            out = out / pvar[None, :, :]
        elif variance:
            out = out / jnp.asarray(variance, out.dtype)
        return out
    # decode_center_size: target [R, C, 4]; prior along `axis`
    if pvar is not None:
        var = pvar[None, :, :] if axis == 0 else pvar[:, None, :]
    elif variance:
        var = jnp.asarray(variance, target.dtype).reshape(1, 1, 4)
    else:
        var = jnp.ones((1, 1, 4), target.dtype)
    if axis == 0:
        pw_b, ph_b = pw[None, :], ph_[None, :]
        pcx_b, pcy_b = pcx[None, :], pcy[None, :]
    else:
        pw_b, ph_b = pw[:, None], ph_[:, None]
        pcx_b, pcy_b = pcx[:, None], pcy[:, None]
    tcx = var[..., 0] * target[..., 0] * pw_b + pcx_b
    tcy = var[..., 1] * target[..., 1] * ph_b + pcy_b
    tw = jnp.exp(var[..., 2] * target[..., 2]) * pw_b
    th = jnp.exp(var[..., 3] * target[..., 3]) * ph_b
    return jnp.stack([tcx - tw / 2, tcy - th / 2,
                      tcx + tw / 2 - norm, tcy + th / 2 - norm], axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference detection.py:819,
    box_coder_op.h EncodeCenterSize/DecodeCenterSize)."""
    if code_type not in ("encode_center_size", "decode_center_size"):
        raise ValueError(f"box_coder: bad code_type {code_type!r}")
    variance = None
    pvar = prior_box_var
    if isinstance(prior_box_var, (list, tuple)):
        variance = [float(v) for v in prior_box_var]
        pvar = None
    return apply_op("box_coder", _k_box_coder, prior_box, pvar,
                    target_box, code_type=code_type,
                    normalized=bool(box_normalized), axis=int(axis),
                    variance=tuple(variance) if variance else ())


def _k_iou_similarity(a, b, normalized):
    norm = 0.0 if normalized else 1.0
    area_a = (a[:, 2] - a[:, 0] + norm) * (a[:, 3] - a[:, 1] + norm)
    area_b = (b[:, 2] - b[:, 0] + norm) * (b[:, 3] - b[:, 1] + norm)
    x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = (jnp.maximum(x2 - x1 + norm, 0.0)
             * jnp.maximum(y2 - y1 + norm, 0.0))
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(inter > 0, inter / union, 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU [N,M] (reference detection.py:765,
    iou_similarity_op.h)."""
    return apply_op("iou_similarity", _k_iou_similarity, x, y,
                    normalized=bool(box_normalized))


# ---------------------------------------------------------------------------
# deform_conv2d (v1/v2)
# ---------------------------------------------------------------------------

def _k_deform_conv2d(x, offset, mask, weight, bias, stride, padding,
                     dilation, dg, groups):
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    sh, sw = stride
    ph_, pw_ = padding
    dh, dw = dilation
    hout = (h + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    wout = (w + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1

    # base sampling positions per output pixel and kernel tap
    oy = jnp.arange(hout) * sh - ph_
    ox = jnp.arange(wout) * sw - pw_
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = (oy[:, None, None, None] + ky[None, None, :, None]
              ).astype(x.dtype)  # [Ho,1,kh,1]
    base_x = (ox[None, :, None, None] + kx[None, None, None, :]
              ).astype(x.dtype)  # [1,Wo,1,kw]
    # offset: [N, dg*2*kh*kw, Ho, Wo] (reference layout: per group,
    # (y, x) interleaved per tap)
    off = offset.reshape(n, dg, kh * kw, 2, hout, wout)
    off_y = off[:, :, :, 0].reshape(n, dg, kh, kw, hout, wout)
    off_x = off[:, :, :, 1].reshape(n, dg, kh, kw, hout, wout)
    if mask is not None:
        mk = mask.reshape(n, dg, kh, kw, hout, wout)
    else:
        mk = None

    cpg = cin // dg  # channels per deformable group

    def sample_group(img_g, oy_g, ox_g, mk_g):
        """img_g [cpg,H,W]; oy/ox [kh,kw,Ho,Wo] -> [cpg,kh,kw,Ho,Wo]."""
        yy = (base_y.transpose(2, 3, 0, 1) + oy_g)  # [kh,kw,Ho,Wo]
        xx = (base_x.transpose(2, 3, 0, 1) + ox_g)
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        ly = yy - y0
        lx = xx - x0
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)

        def gather(yi, xi):
            inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            yc = jnp.clip(yi, 0, h - 1)
            xc = jnp.clip(xi, 0, w - 1)
            v = img_g[:, yc, xc]  # [cpg, kh,kw,Ho,Wo]
            return jnp.where(inb[None], v, 0.0)

        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x0i + 1)
        v10 = gather(y0i + 1, x0i)
        v11 = gather(y0i + 1, x0i + 1)
        out = (v00 * ((1 - ly) * (1 - lx))[None]
               + v01 * ((1 - ly) * lx)[None]
               + v10 * (ly * (1 - lx))[None]
               + v11 * (ly * lx)[None])
        # zero out taps whose sample center fell fully outside
        valid = (yy > -1) & (yy < h) & (xx > -1) & (xx < w)
        out = jnp.where(valid[None], out, 0.0)
        if mk_g is not None:
            out = out * mk_g[None]
        return out

    def per_image(img, oy_i, ox_i, mk_i):
        groups_out = []
        for g in range(dg):
            img_g = jax.lax.dynamic_slice_in_dim(img, g * cpg, cpg, 0)
            mk_g = mk_i[g] if mk_i is not None else None
            groups_out.append(sample_group(img_g, oy_i[g], ox_i[g], mk_g))
        return jnp.concatenate(groups_out, axis=0)  # [cin,kh,kw,Ho,Wo]

    if mk is not None:
        cols = jax.vmap(per_image)(x, off_y, off_x, mk)
    else:
        cols = jax.vmap(lambda img, oy_i, ox_i: per_image(
            img, oy_i, ox_i, None))(x, off_y, off_x)
    # conv as grouped GEMM over the sampled columns: weight
    # [cout, cin/groups, kh, kw], cols [N, cin, kh, kw, Ho, Wo]
    cg = cin // groups
    og = cout // groups
    outs = []
    for g in range(groups):
        cols_g = cols[:, g * cg:(g + 1) * cg]
        w_g = weight[g * og:(g + 1) * og]
        outs.append(jnp.einsum("nckxhw,ockx->nohw", cols_g, w_g))
    out = jnp.concatenate(outs, axis=1)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1 (mask=None) / v2 (reference
    vision/ops.py:423, deformable_conv_op.h): bilinear sampling at
    offset tap positions, then a grouped GEMM over the sampled columns
    (im2col with learned coordinates — MXU-friendly)."""
    return apply_op("deform_conv2d", _k_deform_conv2d, x, offset, mask,
                    weight, bias, stride=_pair(stride),
                    padding=_pair(padding), dilation=_pair(dilation),
                    dg=int(deformable_groups), groups=int(groups))


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = _pair(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._dg = deformable_groups
        self._groups = groups
        k = 1.0 / math.sqrt(in_channels * kh * kw)
        from ..nn.initializer import Uniform

        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr, default_initializer=Uniform(-k, k))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr,
                default_initializer=Constant(0.0), is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._dg, self._groups, mask)


# ---------------------------------------------------------------------------
# layers + remaining host-side ops
# ---------------------------------------------------------------------------

class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, sampling_ratio=2,
                         aligned=aligned)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (reference vision/ops.py nms): host/numpy — the kept
    index list is data-dependent-length, so this is an eager-only op
    like the reference CPU kernel."""
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes,
                   np.float32)
    s = (np.asarray(scores._value if isinstance(scores, Tensor)
                    else scores, np.float32) if scores is not None
         else np.ones(len(b), np.float32))
    cat = (np.asarray(category_idxs._value
                      if isinstance(category_idxs, Tensor)
                      else category_idxs)
           if category_idxs is not None else None)

    def _nms_single(idxs):
        order = idxs[np.argsort(-s[idxs])]
        keep = []
        suppressed = np.zeros(len(b), bool)
        areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        for i in order:
            if suppressed[i]:
                continue
            keep.append(int(i))
            xx1 = np.maximum(b[i, 0], b[idxs, 0])
            yy1 = np.maximum(b[i, 1], b[idxs, 1])
            xx2 = np.minimum(b[i, 2], b[idxs, 2])
            yy2 = np.minimum(b[i, 3], b[idxs, 3])
            inter = (np.maximum(xx2 - xx1, 0)
                     * np.maximum(yy2 - yy1, 0))
            iou = inter / np.maximum(areas[i] + areas[idxs] - inter,
                                     1e-10)
            suppressed[idxs[iou > iou_threshold]] = True
            suppressed[i] = True
        return keep

    if cat is None:
        keep = _nms_single(np.arange(len(b)))
    else:
        keep = []
        for c in (categories if categories is not None
                  else np.unique(cat)):
            keep.extend(_nms_single(np.where(cat == c)[0]))
        keep = sorted(keep, key=lambda i: -s[i])
    if top_k is not None:
        keep = keep[:top_k]
    return to_tensor(np.asarray(keep, np.int64))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference
    distribute_fpn_proposals_op.h). Host/numpy (output row counts are
    data-dependent), eager only."""
    rois = np.asarray(fpn_rois._value if isinstance(fpn_rois, Tensor)
                      else fpn_rois, np.float32)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 0.0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        # per-image counts -> per-level, per-image counts (reference
        # MultiLevelRoIsNum outputs), so each level's output can feed
        # roi_align's boxes_num with image boundaries intact
        rn = np.asarray(rois_num._value if isinstance(rois_num, Tensor)
                        else rois_num).astype(np.int64)
        img_of = np.repeat(np.arange(len(rn)), rn)
    else:
        img_of = np.zeros(len(rois), np.int64)
        rn = np.asarray([len(rois)], np.int64)
    outs, restore = [], np.empty(len(rois), np.int64)
    nums = []
    pos = 0
    for L in range(min_level, max_level + 1):
        idx = np.where(lvl == L)[0]
        # stable by image so per-image counts describe contiguous rows
        idx = idx[np.argsort(img_of[idx], kind="stable")]
        outs.append(to_tensor(rois[idx]))
        nums.append(to_tensor(np.bincount(
            img_of[idx], minlength=len(rn)).astype(np.int32)))
        restore[idx] = np.arange(pos, pos + len(idx))
        pos += len(idx)
    return outs, to_tensor(restore), nums


# ---------------------------------------------------------------------------
# matrix_nms (SOLOv2 decay NMS)
# ---------------------------------------------------------------------------

def _k_matrix_nms(bboxes, scores, score_threshold, post_threshold,
                  nms_top_k, keep_top_k, use_gaussian, gaussian_sigma,
                  background_label, normalized):
    """One batch of Matrix NMS (matrix_nms_op.cc:81-150): instead of
    hard suppression, every candidate's score DECAYS by the minimum
    over higher-scored same-class boxes i of f(iou_ij)/f(max_iou_i) —
    linear (1-iou)/(1-max_iou) or gaussian
    exp((max_iou^2 - iou^2) * sigma).

    Static-shape formulation: per class, candidates sort by score
    (nms_top_k cap), the full IoU matrix is built once, max_iou is a
    prefix max, and decays reduce with a masked min — no data-
    dependent loops. Output is PADDED to keep_top_k rows per image
    ([-1, 0, 0, 0, 0, 0] padding) + the true count.
    """
    N, C, M = scores.shape
    k = min(int(nms_top_k), M) if nms_top_k > 0 else M
    sigma = jnp.float32(gaussian_sigma)

    def per_class(boxes, sc):
        # boxes [M, 4], sc [M] (one class, one image)
        order = jnp.argsort(-sc)[:k]
        s = sc[order]
        b = boxes[order]
        iou = _k_iou_similarity(b, b, normalized)     # [k, k]
        tri = jnp.tril(iou, -1)                       # j row, i<j cols
        max_iou = jnp.max(tri, axis=1)                # max_iou[i]
        if use_gaussian:
            decay = jnp.exp((max_iou[None, :] ** 2 - tri ** 2) * sigma)
        else:
            decay = (1.0 - tri) / jnp.maximum(1.0 - max_iou[None, :],
                                              1e-10)
        # only i < j count; elsewhere decay 1
        mask = jnp.tril(jnp.ones((k, k), bool), -1)
        decay = jnp.where(mask, decay, 1.0)
        dmin = jnp.min(decay, axis=1)
        valid = s > score_threshold
        return jnp.where(valid, s * dmin, -1.0), b

    def per_image(boxes, sc):
        # sc [C, M]; skip background
        cls_ids = jnp.arange(C)
        dec, bxs = jax.vmap(lambda c_sc: per_class(boxes, c_sc))(sc)
        # dec [C, k], bxs [C, k, 4]
        if background_label >= 0:
            dec = dec.at[background_label].set(-1.0)
        flat = dec.reshape(-1)
        fbox = bxs.reshape(-1, 4)
        fcls = jnp.repeat(cls_ids, k).astype(jnp.float32)
        kk = min(int(keep_top_k), flat.shape[0]) if keep_top_k > 0 \
            else flat.shape[0]
        top, pos = jax.lax.top_k(flat, kk)
        keep = top > post_threshold
        rows = jnp.concatenate(
            [jnp.where(keep, fcls[pos], -1.0)[:, None],
             jnp.where(keep, top, 0.0)[:, None],
             jnp.where(keep[:, None], fbox[pos], 0.0)], axis=1)
        return rows, jnp.sum(keep).astype(jnp.int32)

    return jax.vmap(per_image)(bboxes, scores)


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0,
               normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (matrix_nms_op.cc:1; paddle.vision.ops.matrix_nms).
    bboxes [N, M, 4], scores [N, C, M]. Returns (out [N, keep_top_k,
    6] with rows [class, score, x1, y1, x2, y2] padded by class -1,
    rois_num [N])."""
    out, num = apply_op(
        "matrix_nms", _k_matrix_nms, bboxes, scores,
        score_threshold=float(score_threshold),
        post_threshold=float(post_threshold),
        nms_top_k=int(nms_top_k), keep_top_k=int(keep_top_k),
        use_gaussian=bool(use_gaussian),
        gaussian_sigma=float(gaussian_sigma),
        background_label=int(background_label),
        normalized=bool(normalized))
    if return_rois_num:
        return out, num
    return out


__all__.append("matrix_nms")
