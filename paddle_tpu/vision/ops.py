"""paddle.vision.ops (reference: python/paddle/vision/ops.py — yolo/roi
ops + DeformConv; round-1 carries box utilities + nms)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = ["nms", "box_coder", "RoIAlign", "roi_align", "DeformConv2D"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    b = np.asarray(boxes._value, np.float32)
    s = (np.asarray(scores._value, np.float32) if scores is not None
         else np.ones(len(b), np.float32))
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    if top_k is not None:
        keep = keep[:top_k]
    return to_tensor(np.asarray(keep, np.int64))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    raise NotImplementedError("box_coder: planned (detection suite)")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    raise NotImplementedError("roi_align: planned (detection suite)")


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        raise NotImplementedError("RoIAlign: planned (detection suite)")


class DeformConv2D:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError("DeformConv2D: planned (detection suite)")
