"""paddle.vision.datasets (reference: python/paddle/vision/datasets/).
Zero-egress environment: datasets load from local files when present,
else generate deterministic synthetic data with the right shapes —
keeping training scripts runnable end-to-end."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "DatasetFolder", "ImageFolder"]


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py. Loads idx
    files from `image_path`/`label_path` or DATA_HOME; falls back to a
    synthetic digit set (deterministic) when files are absent."""

    NUM_SYNTH = 2048

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    def _load(self, image_path, label_path):
        home = os.environ.get("DATA_HOME",
                              os.path.expanduser("~/.cache/paddle/dataset"))
        stem = "train" if self.mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            home, "mnist", f"{stem}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            home, "mnist", f"{stem}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8)
            return images, labels.astype(np.int64)
        # synthetic fallback
        rng = np.random.RandomState(42 if self.mode == "train" else 7)
        n = self.NUM_SYNTH
        labels = rng.randint(0, 10, n).astype(np.int64)
        images = np.zeros((n, 28, 28), np.uint8)
        for i, lbl in enumerate(labels):
            img = rng.randint(0, 30, (28, 28))
            r0, c0 = 4 + (lbl % 3) * 3, 4 + (lbl // 3) * 3
            img[r0:r0 + 12, c0:c0 + 8] = 200 + (lbl * 5) % 55
            images[i] = img
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 127.5 - 1.0
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_SYNTH = 1024
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        rng = np.random.RandomState(13 if mode == "train" else 31)
        n = self.NUM_SYNTH
        self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)
        self.images = rng.randint(0, 255, (n, 3, 32, 32)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Cifar10):
    NUM_CLASSES = 102


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".jpg", ".jpeg", ".png", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise RuntimeError("PIL unavailable; use .npy files")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".jpg", ".jpeg", ".png", ".bmp", ".npy")
        self.samples = [os.path.join(root, f) for f in sorted(
            os.listdir(root)) if f.lower().endswith(extensions)]
        self.loader = loader or DatasetFolder._default_loader

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
