"""InceptionV3 (reference: python/paddle/vision/models/inceptionv3.py).
Compact faithful variant (A/B/C/D/E blocks)."""
from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D,
                   Dropout, Layer, Linear, MaxPool2D, ReLU, Sequential)

__all__ = ["InceptionV3", "inception_v3"]


def conv_bn(inp, oup, kernel, stride=1, padding=0):
    return Sequential(
        Conv2D(inp, oup, kernel, stride=stride, padding=padding,
               bias_attr=False),
        BatchNorm2D(oup), ReLU())


class InceptionA(Layer):
    def __init__(self, inp, pool_features):
        super().__init__()
        self.b1 = conv_bn(inp, 64, 1)
        self.b5 = Sequential(conv_bn(inp, 48, 1), conv_bn(48, 64, 5,
                                                          padding=2))
        self.b3 = Sequential(conv_bn(inp, 64, 1),
                             conv_bn(64, 96, 3, padding=1),
                             conv_bn(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1),
                             conv_bn(inp, pool_features, 1))

    def forward(self, x):
        from ...ops.manipulation import concat

        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                      axis=1)


class InceptionB(Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = conv_bn(inp, 384, 3, stride=2)
        self.b3d = Sequential(conv_bn(inp, 64, 1),
                              conv_bn(64, 96, 3, padding=1),
                              conv_bn(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        from ...ops.manipulation import concat

        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class InceptionC(Layer):
    def __init__(self, inp, c7):
        super().__init__()
        self.b1 = conv_bn(inp, 192, 1)
        self.b7 = Sequential(conv_bn(inp, c7, 1),
                             conv_bn(c7, c7, (1, 7), padding=(0, 3)),
                             conv_bn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(conv_bn(inp, c7, 1),
                              conv_bn(c7, c7, (7, 1), padding=(3, 0)),
                              conv_bn(c7, c7, (1, 7), padding=(0, 3)),
                              conv_bn(c7, c7, (7, 1), padding=(3, 0)),
                              conv_bn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1),
                             conv_bn(inp, 192, 1))

    def forward(self, x):
        from ...ops.manipulation import concat

        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                      axis=1)


class InceptionD(Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = Sequential(conv_bn(inp, 192, 1),
                             conv_bn(192, 320, 3, stride=2))
        self.b7 = Sequential(conv_bn(inp, 192, 1),
                             conv_bn(192, 192, (1, 7), padding=(0, 3)),
                             conv_bn(192, 192, (7, 1), padding=(3, 0)),
                             conv_bn(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        from ...ops.manipulation import concat

        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class InceptionE(Layer):
    def __init__(self, inp):
        super().__init__()
        self.b1 = conv_bn(inp, 320, 1)
        self.b3_1 = conv_bn(inp, 384, 1)
        self.b3_2a = conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = Sequential(conv_bn(inp, 448, 1),
                               conv_bn(448, 384, 3, padding=1))
        self.bd_2a = conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.bd_2b = conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1),
                             conv_bn(inp, 192, 1))

    def forward(self, x):
        from ...ops.manipulation import concat

        b3 = self.b3_1(x)
        b3 = concat([self.b3_2a(b3), self.b3_2b(b3)], axis=1)
        bd = self.bd_1(x)
        bd = concat([self.bd_2a(bd), self.bd_2b(bd)], axis=1)
        return concat([self.b1(x), b3, bd, self.bp(x)], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            conv_bn(3, 32, 3, stride=2), conv_bn(32, 32, 3),
            conv_bn(32, 64, 3, padding=1), MaxPool2D(3, 2),
            conv_bn(64, 80, 1), conv_bn(80, 192, 3), MaxPool2D(3, 2))
        self.mixed = Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.mixed(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten

            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable (no egress)")
    return InceptionV3(**kwargs)
