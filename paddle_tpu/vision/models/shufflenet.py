"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, ChannelShuffle, Conv2D,
                   Layer, Linear, MaxPool2D, ReLU, Sequential)

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0"]

_CFG = {"0.25": [24, 24, 48, 96, 512], "0.5": [24, 48, 96, 192, 1024],
        "1.0": [24, 116, 232, 464, 1024], "1.5": [24, 176, 352, 704, 1024],
        "2.0": [24, 244, 488, 976, 2048]}


def _cb(inp, oup, k, stride=1, padding=0, groups=1, act=True):
    layers = [Conv2D(inp, oup, k, stride=stride, padding=padding,
                     groups=groups, bias_attr=False), BatchNorm2D(oup)]
    if act:
        layers.append(ReLU())
    return Sequential(*layers)


class ShuffleUnit(Layer):
    def __init__(self, inp, oup, stride):
        super().__init__()
        self.stride = stride
        branch = oup // 2
        if stride == 1:
            self.b2 = Sequential(
                _cb(inp // 2, branch, 1),
                _cb(branch, branch, 3, stride=1, padding=1, groups=branch,
                    act=False),
                _cb(branch, branch, 1))
        else:
            self.b1 = Sequential(
                _cb(inp, inp, 3, stride=stride, padding=1, groups=inp,
                    act=False),
                _cb(inp, branch, 1))
            self.b2 = Sequential(
                _cb(inp, branch, 1),
                _cb(branch, branch, 3, stride=stride, padding=1,
                    groups=branch, act=False),
                _cb(branch, branch, 1))
        self.shuffle = ChannelShuffle(2)

    def forward(self, x):
        from ...ops.manipulation import concat, split

        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.b2(x2)], axis=1)
        else:
            out = concat([self.b1(x), self.b2(x)], axis=1)
        return self.shuffle(out)


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        cfg = _CFG[f"{scale:.2f}".rstrip("0").rstrip(".")
                   if f"{scale}" not in _CFG else f"{scale}"]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _cb(3, cfg[0], 3, stride=2, padding=1)
        self.pool1 = MaxPool2D(3, 2, padding=1)
        stages = []
        inp = cfg[0]
        for idx, repeat in enumerate([4, 8, 4]):
            oup = cfg[idx + 1]
            units = [ShuffleUnit(inp, oup, 2)]
            for _ in range(repeat - 1):
                units.append(ShuffleUnit(oup, oup, 1))
            stages.append(Sequential(*units))
            inp = oup
        self.stages = Sequential(*stages)
        self.conv_last = _cb(inp, cfg[-1], 1)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(cfg[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.pool1(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten

            x = self.fc(flatten(x, 1))
        return x


def _make(scale, pretrained, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable (no egress)")
    return ShuffleNetV2(scale=scale, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _make(0.25, pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _make(0.5, pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _make(1.0, pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _make(1.5, pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _make(2.0, pretrained, **kwargs)
