"""GoogLeNet (reference: python/paddle/vision/models/googlenet.py)."""
from ...nn import (AdaptiveAvgPool2D, Conv2D, Dropout, Layer, Linear,
                   MaxPool2D, ReLU, Sequential)

__all__ = ["GoogLeNet", "googlenet"]


class Inception(Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = Sequential(Conv2D(inp, c1, 1), ReLU())
        self.b2 = Sequential(Conv2D(inp, c3r, 1), ReLU(),
                             Conv2D(c3r, c3, 3, padding=1), ReLU())
        self.b3 = Sequential(Conv2D(inp, c5r, 1), ReLU(),
                             Conv2D(c5r, c5, 5, padding=2), ReLU())
        self.b4 = Sequential(MaxPool2D(3, 1, padding=1),
                             Conv2D(inp, proj, 1), ReLU())

    def forward(self, x):
        from ...ops.manipulation import concat

        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            Conv2D(3, 64, 7, stride=2, padding=3), ReLU(),
            MaxPool2D(3, 2, padding=1),
            Conv2D(64, 64, 1), ReLU(),
            Conv2D(64, 192, 3, padding=1), ReLU(),
            MaxPool2D(3, 2, padding=1))
        self.i3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, 2, padding=1)
        self.i4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, 2, padding=1)
        self.i5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x))))))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten

            x = self.fc(self.dropout(flatten(x, 1)))
        # reference returns (out, aux1, aux2); aux heads omitted in eval
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable (no egress)")
    return GoogLeNet(**kwargs)
