"""DenseNet (reference: python/paddle/vision/models/densenet.py)."""
from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D,
                   Layer, Linear, MaxPool2D, ReLU, Sequential)

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {121: (64, 32, [6, 12, 24, 16]), 161: (96, 48, [6, 12, 36, 24]),
        169: (64, 32, [6, 12, 32, 32]), 201: (64, 32, [6, 12, 48, 32]),
        264: (64, 32, [6, 12, 64, 48])}


class DenseLayer(Layer):
    def __init__(self, inp, growth_rate, bn_size):
        super().__init__()
        self.norm1 = BatchNorm2D(inp)
        self.relu = ReLU()
        self.conv1 = Conv2D(inp, bn_size * growth_rate, 1, bias_attr=False)
        self.norm2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3,
                            padding=1, bias_attr=False)

    def forward(self, x):
        from ...ops.manipulation import concat

        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        return concat([x, out], axis=1)


class Transition(Layer):
    def __init__(self, inp, oup):
        super().__init__()
        self.norm = BatchNorm2D(inp)
        self.relu = ReLU()
        self.conv = Conv2D(inp, oup, 1, bias_attr=False)
        self.pool = AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        num_init, growth, block_cfg = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [Conv2D(3, num_init, 7, stride=2, padding=3,
                        bias_attr=False),
                 BatchNorm2D(num_init), ReLU(), MaxPool2D(3, 2, padding=1)]
        ch = num_init
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(DenseLayer(ch, growth, bn_size))
                ch += growth
            if i != len(block_cfg) - 1:
                feats.append(Transition(ch, ch // 2))
                ch //= 2
        feats.extend([BatchNorm2D(ch), ReLU()])
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten

            x = self.classifier(flatten(x, 1))
        return x


def _densenet(depth, pretrained, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable (no egress)")
    return DenseNet(layers=depth, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
