"""paddle.vision.models (reference:
python/paddle/vision/models/__init__.py:15-34 — ResNet family, VGG,
MobileNetV1/2, LeNet, DenseNet, AlexNet, GoogLeNet, InceptionV3,
SqueezeNet, ShuffleNetV2, ResNeXt/wide variants)."""
from .lenet import LeNet
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, resnext50_32x4d, resnext101_32x8d,
                     wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenet import (MobileNetV1, MobileNetV2, mobilenet_v1,
                        mobilenet_v2)
from .alexnet import AlexNet, alexnet
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .googlenet import GoogLeNet, googlenet
from .inception import InceptionV3, inception_v3
from .shufflenet import (ShuffleNetV2, shufflenet_v2_x0_25,
                         shufflenet_v2_x0_5, shufflenet_v2_x1_0,
                         shufflenet_v2_x1_5, shufflenet_v2_x2_0)

__all__ = [
    "LeNet", "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
    "resnet152", "resnext50_32x4d", "resnext101_32x8d", "wide_resnet50_2",
    "wide_resnet101_2", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2",
    "AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264", "GoogLeNet", "googlenet", "InceptionV3", "inception_v3",
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
    "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
]
