"""Entry point for `python -m paddle_tpu.monitor`."""
import sys

from .cli import main

sys.exit(main())
