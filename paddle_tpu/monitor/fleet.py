"""paddle_tpu.monitor.fleet — fleet-wide telemetry aggregation +
straggler detection (ISSUE 15).

Multi-rank runs leave one telemetry trail PER RANK (exporter jsonl
spools, flight dump bundles); nothing merged them, so "which rank is
slow" was a grep exercise. This module is the merge + skew layer:

  * merge_records() — N per-rank records into ONE fleet view:
    monotonic COUNTERS sum (`step/count`, `comm/*/bytes`, ...);
    GAUGES (step/last_*, queue depths, mem/* watermarks — see
    `is_gauge`) stay per-rank (summing a watermark is a lie);
    HISTOGRAMS bucket-merge (the Histogram boundaries are a pure
    function of their config, so per-rank bucket counts add — the
    fleet p99 is exact over the union of observations).
  * straggler_report() — per-rank mean step time
    (`step/total_time_us / step/count`) vs the FLEET MEDIAN; ranks
    slower than `threshold`× the median (PADDLE_MONITOR_STRAGGLER_X,
    default 1.25) are flagged, and the slowest rank is attributed
    with its longest flight spans (`*_end` ring events' dur_us) when
    the record came from a dump bundle — "rank 3 is 1.8× the median
    and spent its time in collective/all_reduce" instead of a bare
    number.
  * load_spool() / fleet_view() — the offline entry: exporter
    `.jsonl` trails (last flush per rank) and flight dump bundles
    both parse into records; `python -m paddle_tpu.monitor fleet`
    wraps fleet_view().
  * fleet_snapshot() — the LIVE entry for a running multi-rank job:
    every rank publishes its telemetry_snapshot() to the rank-0 KV
    store (the store_collective bootstrap the eager collectives
    already stand up), rank 0 merges. Collective-style discipline:
    all ranks must call it the same number of times.
"""
from __future__ import annotations

import itertools
import json
import os
import re
import time

from ..core import monitor as _cmon
from ..core.monitor import Histogram, snapshot_quantile  # noqa: F401
from . import flight as _flight

__all__ = ["is_gauge", "merge_hists", "merge_records",
           "straggler_report", "load_spool", "load_records",
           "fleet_view", "fleet_snapshot", "top_spans",
           "slowest_program", "scrape_records", "scrape_view",
           "alert_rollup"]


# -- counter-vs-gauge classification ---------------------------------------
# The registry holds both monotonic counters (stat_add) and
# overwrite/watermark gauges (stat_set/maximum) under one namespace;
# merging must not sum a watermark. The split is by NAME (the same
# heuristic a Prometheus relabeling would encode) — kept here, in one
# place, so the CLI and the live merge agree.

_GAUGE_PREFIXES = ("mem/", "step/mem/", "step/attrib/",
                   "flight/events", "flight/ring/",
                   "serve/kv_blocks/", "chaos/", "sanitize/",
                   "perf/", "alerts/")
_GAUGE_SUFFIXES = ("/queue_depth", "/throughput", "/healthy",
                   "/armed", "/steps_per_dispatch")
_GAUGE_SUBSTR = ("/last_", "/lr_e9", "last_loss", "last_time")


def is_gauge(name):
    """True for stats whose fleet merge must stay per-rank (gauges,
    watermarks) rather than summing (counters)."""
    if name.startswith(_GAUGE_PREFIXES):
        return True
    if name.endswith(_GAUGE_SUFFIXES):
        return True
    return any(s in name for s in _GAUGE_SUBSTR)


def merge_hists(snaps):
    """Bucket-merge Histogram.snapshot() dicts. Returns a merged
    snapshot dict, or None for no (usable) inputs. A LIVE fleet is
    allowed to be mixed-schema — a rank relaunched with different
    histogram-config knobs (PADDLE_MONITOR_HIST_LO and siblings), or
    a spool predating a boundary
    change, must degrade (majority-schema merge + a skip counter)
    rather than crash the whole straggler report: snaps are grouped
    by boundary config, the group holding the most observations
    merges, the rest count under monitor/fleet/hist_schema_skips."""
    groups = {}
    for s in snaps:
        if not isinstance(s, dict):
            continue
        try:
            key = (float(s["lo"]), int(s["per_decade"]),
                   int(s["decades"]))
        except (KeyError, TypeError, ValueError):
            _cmon.stat_add("monitor/fleet/hist_schema_skips", 1)
            continue
        groups.setdefault(key, []).append(s)
    if not groups:
        return None
    key = max(groups, key=lambda k: (
        sum(int(s.get("count", 0)) for s in groups[k]),
        len(groups[k])))
    skipped = sum(len(v) for k, v in groups.items() if k != key)
    if skipped:
        _cmon.stat_add("monitor/fleet/hist_schema_skips", skipped)
    base = Histogram(lo=key[0], per_decade=key[1], decades=key[2])
    for s in groups[key]:
        base.merge(s)
    return base.snapshot()


def merge_records(records):
    """N per-rank records ({"rank", "stats", "hists"}) -> one fleet
    view: {"ranks", "counters" (summed), "gauges" (per-rank),
    "hists" (bucket-merged + per-rank counts)}."""
    records = list(records)
    ranks = [int(r.get("rank", i)) for i, r in enumerate(records)]
    counters = {}
    gauges = {}
    hist_by_name = {}
    for rec, rank in zip(records, ranks):
        for k, v in (rec.get("stats") or {}).items():
            # non-numeric values (a mixed-schema spool smuggling
            # strings into the stat namespace) cannot sum — keep
            # them visible per-rank instead of crashing the merge
            if is_gauge(k) or isinstance(v, str) \
                    or not isinstance(v, (int, float)):
                gauges.setdefault(k, {})[str(rank)] = v
            else:
                counters[k] = counters.get(k, 0) + v
        for k, s in (rec.get("hists") or {}).items():
            hist_by_name.setdefault(k, []).append((rank, s))
    hists = {}
    for k, pairs in hist_by_name.items():
        merged = merge_hists([s for _, s in pairs])
        if merged is not None:
            merged["rank_counts"] = {
                str(r): (int(s.get("count", 0))
                         if isinstance(s, dict) else 0)
                for r, s in pairs}
            hists[k] = merged
    return {"ranks": sorted(set(ranks)), "counters": counters,
            "gauges": gauges, "hists": hists}


def top_spans(flight_tail, n=5):
    """Longest completed spans in a flight ring tail: `*_end` events
    carry dur_us — the attribution payload for a flagged
    straggler."""
    spans = [ev for ev in (flight_tail or [])
             if isinstance(ev, dict)
             and str(ev.get("kind", "")).endswith("_end")
             and ev.get("dur_us") is not None]
    spans.sort(key=lambda e: -int(e["dur_us"]))
    return [{"kind": ev["kind"][:-4], "name": ev.get("name"),
             "dur_us": int(ev["dur_us"])} for ev in spans[:n]]


# the ISSUE-16 per-program dispatch histograms — present in any spool
# whose rank ran with PADDLE_PERF_DISPATCH on
_DISPATCH_HIST = re.compile(r"^jit/hist/(.+)/dispatch_us$")


def slowest_program(hists):
    """The program that consumed the most measured dispatch time in a
    rank's per-program histograms (max by hist sum — count × mean, not
    a single outlier). None when the rank's spool predates the perf
    plane or ran with dispatch timing off."""
    best = None
    for k, snap in (hists or {}).items():
        m = _DISPATCH_HIST.match(k)
        if not m or not isinstance(snap, dict) \
                or not snap.get("count"):
            continue
        tot = float(snap.get("sum", 0.0))
        if best is None or tot > best[0]:
            best = (tot, m.group(1), snap)
    if best is None:
        return None
    tot, name, snap = best
    return {"program": name, "total_us": int(tot),
            "count": int(snap.get("count", 0)),
            "p50_us": round(snapshot_quantile(snap, 0.5), 1)}


def straggler_threshold():
    """PADDLE_MONITOR_STRAGGLER_X — mean-step-time skew vs the fleet
    median above which a rank is flagged (default 1.25)."""
    return max(1.0, _flight._env_float("PADDLE_MONITOR_STRAGGLER_X",
                                       1.25))


def straggler_report(records, threshold=None):
    """Per-rank mean step time vs the fleet median; ranks above
    `threshold`x median are stragglers, each flagged rank gets its
    top flight spans attached (when its record carries a flight tail
    — dump-bundle inputs do) and its slowest PROGRAM (when its
    per-program dispatch histograms are in the spool — ISSUE 16),
    so the report names the program dragging the rank, not just the
    span kind."""
    if threshold is None:
        threshold = straggler_threshold()
    step_ms = {}
    tails = {}
    rank_hists = {}
    for i, rec in enumerate(records):
        rank = int(rec.get("rank", i))
        stats = rec.get("stats") or {}
        n = stats.get("step/count", 0)
        if n:
            step_ms[rank] = round(
                stats.get("step/total_time_us", 0) / n / 1e3, 3)
        if rec.get("flight_tail"):
            tails[rank] = rec["flight_tail"]
        if rec.get("hists"):
            rank_hists[rank] = rec["hists"]
    out = {"threshold": threshold,
           "step_ms": {str(r): v for r, v in sorted(step_ms.items())},
           "median_ms": None, "stragglers": [], "slowest": None}
    if not step_ms:
        return out
    times = sorted(step_ms.values())
    # TRUE median (even N averages the middles): the upper-middle
    # shortcut makes the slow rank of a 2-rank fleet its own
    # median — skew 1.0, never flagged
    mid = len(times) // 2
    median = (times[mid] if len(times) % 2
              else (times[mid - 1] + times[mid]) / 2.0)
    out["median_ms"] = median
    slowest = max(step_ms, key=lambda r: step_ms[r])
    out["slowest"] = slowest
    for rank in sorted(step_ms):
        skew = step_ms[rank] / median if median else 1.0
        if skew > threshold:
            entry = {"rank": rank, "step_ms": step_ms[rank],
                     "skew": round(skew, 3)}
            if rank in tails:
                entry["top_spans"] = top_spans(tails[rank])
            prog = slowest_program(rank_hists.get(rank))
            if prog is not None:
                entry["slowest_program"] = prog
            out["stragglers"].append(entry)
    return out


# -- offline loading -------------------------------------------------------

def load_spool(path):
    """{rank: record} from ONE artifact: a MetricsExporter `.jsonl`
    trail (last flush per rank wins) or a flight dump bundle (its
    embedded telemetry + flight tail). Raises ValueError on
    unparsable input — the CLI's exit-2 contract."""
    with open(path) as f:
        text = f.read()
    out = {}
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and (doc.get("schema") or ""
                                  ).startswith("paddle_tpu.flight"):
        tele = doc.get("telemetry") or {}
        rank = int(doc.get("rank", 0))
        out[rank] = {"rank": rank,
                     "stats": tele.get("stats") or {},
                     "hists": tele.get("hists") or {},
                     "flight_tail": doc.get("flight_tail"),
                     "source": path}
        return out
    if isinstance(doc, dict) and "stats" in doc:
        # a single telemetry_snapshot() saved as-is
        rank = int(doc.get("rank", 0))
        out[rank] = {"rank": rank, "stats": doc["stats"],
                     "hists": doc.get("hists") or {}, "source": path}
        return out
    bad = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            bad += 1
            continue
        if not isinstance(rec, dict) or "stats" not in rec:
            bad += 1
            continue
        rank = int(rec.get("rank", 0))
        out[rank] = {"rank": rank, "stats": rec["stats"],
                     "hists": rec.get("hists") or {}, "source": path}
    if not out:
        raise ValueError(
            f"{path}: no exporter records or flight bundle found"
            + (f" ({bad} unparsable line(s))" if bad else ""))
    return out


def load_records(paths):
    """Merge load_spool() over many artifacts; a later file's record
    for the same rank wins (pass newest last)."""
    ranks = {}
    for p in paths:
        ranks.update(load_spool(p))
    return [ranks[r] for r in sorted(ranks)]


def fleet_view(paths, threshold=None):
    """The `monitor fleet` payload: merged counters/gauges/hists over
    every rank artifact plus the straggler report."""
    records = load_records(paths)
    view = merge_records(records)
    view["stragglers"] = straggler_report(records,
                                          threshold=threshold)
    view["sources"] = [r.get("source") for r in records]
    view["alerts"] = alert_rollup(records)
    return view


# -- fleet-wide alert rollup (ISSUE 20) ------------------------------------

def alert_rollup(records):
    """Any-rank-firing rollup of per-rank alert states.

    Prefers the scraped /alertz payload (rec["alerts"], exact rule
    states); falls back to inferring from the alerts/* stats every
    armed rank publishes: `alerts/<name>/firing` 1 -> firing, 0 with
    transitions>0 -> resolved, 0 with none -> ok. A rank with no
    alerts/* stats at all simply never armed the engine — absent
    from `armed_ranks`, not an error (a fleet mixing armed frontends
    with unarmed trainers is normal).

    Returns {"any_firing", "armed_ranks",
             "rules": {name: {"firing": [ranks], "resolved": [...],
                              "ok": [...]}}}.
    """
    armed_ranks = []
    rules = {}

    def _mark(name, state, rank):
        slot = rules.setdefault(
            name, {"firing": [], "resolved": [], "ok": []})
        if rank not in slot[state]:
            slot[state].append(rank)

    for rec in records:
        rank = int(rec.get("rank", 0))
        payload = rec.get("alerts")
        if isinstance(payload, dict) and payload.get("armed"):
            armed_ranks.append(rank)
            for r in payload.get("rules") or []:
                st = r.get("state")
                _mark(r.get("name", "?"),
                      "firing" if st == "firing" else
                      "resolved" if st == "resolved" else "ok",
                      rank)
            continue
        stats = rec.get("stats") or {}
        names = {k.split("/")[1] for k in stats
                 if k.startswith("alerts/") and k.count("/") == 2}
        if not names:
            continue
        armed_ranks.append(rank)
        for name in names:
            if stats.get(f"alerts/{name}/firing", 0):
                _mark(name, "firing", rank)
            elif stats.get(f"alerts/{name}/transitions", 0):
                _mark(name, "resolved", rank)
            else:
                _mark(name, "ok", rank)
    for slot in rules.values():
        for ranks in slot.values():
            ranks.sort()
    return {"any_firing": any(s["firing"] for s in rules.values()),
            "armed_ranks": sorted(armed_ranks),
            "rules": rules}


# -- live scraping (HTTP pull from monitor.server) -------------------------

def _scrape_json(base, path, timeout):
    import urllib.request

    req = urllib.request.Request(
        base + path, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def scrape_records(targets, timeout=5.0, with_flight=True):
    """Pull live telemetry from running monitor.server instances.

    `targets` are `host:port` strings (scheme optional). Each
    reachable target contributes the same record shape load_spool()
    produces from a dump bundle — {rank, stats, hists[, flight_tail],
    source} — so merge/straggler output is byte-compatible with the
    bundle-driven path. Unreachable or unparsable targets are
    collected into `failures` ({target: "ExcType: msg"}) instead of
    raising: a half-dead fleet still yields a partial report.
    Returns (records, failures); records are deduped per rank (last
    target wins) and sorted, mirroring load_records().
    """
    records, failures = [], {}
    for t in targets:
        base = (t if "//" in t else "http://" + t).rstrip("/")
        try:
            snap = _scrape_json(base, "/metrics?format=json", timeout)
            if not isinstance(snap, dict) or "stats" not in snap:
                raise ValueError(
                    "no telemetry snapshot in /metrics?format=json")
            rec = {"rank": int(snap.get("rank", 0)),
                   "stats": snap.get("stats") or {},
                   "hists": snap.get("hists") or {},
                   "source": base}
            try:  # status page is decorative; telemetry is the contract
                rec["status"] = _scrape_json(base, "/statusz", timeout)
            except Exception:
                pass
            try:  # exact rule states beat the stats-inferred rollup
                al = _scrape_json(base, "/alertz", timeout)
                if isinstance(al, dict) and al.get("armed"):
                    rec["alerts"] = al
            except Exception:
                pass
            if with_flight:
                try:
                    fl = _scrape_json(base, "/flightz", timeout)
                    if isinstance(fl, dict) and fl.get("events"):
                        rec["flight_tail"] = fl["events"]
                except Exception:
                    pass
            records.append(rec)
        except Exception as e:  # noqa: BLE001 — per-target isolation
            failures[t] = f"{type(e).__name__}: {e}"
            _cmon.stat_add("monitor/fleet/scrape_failures", 1)
    ranks = {}
    for rec in records:
        ranks[rec["rank"]] = rec
    return [ranks[r] for r in sorted(ranks)], failures


def scrape_view(records, threshold=None):
    """The live twin of fleet_view(): merged counters/gauges/hists
    plus the straggler report over scrape_records() output."""
    view = merge_records(records)
    view["stragglers"] = straggler_report(records,
                                          threshold=threshold)
    view["sources"] = [r.get("source") for r in records]
    view["alerts"] = alert_rollup(records)
    return view


# -- live fleet snapshot (rank-0 KV store) ---------------------------------

_snap_seq = itertools.count(1)


def fleet_snapshot(timeout=60.0):
    """Live multi-rank merge over the store_collective bootstrap:
    every rank publishes its telemetry_snapshot() under a
    per-invocation key; rank 0 polls until all `world_size` records
    land and returns the merged view (+ stragglers); other ranks
    return None. Must be called collectively (same count on every
    rank) — the per-call sequence number is the rendezvous key.
    world_size == 1 short-circuits to a local one-rank view."""
    from ..distributed.env import peek_rank, peek_world_size
    from . import telemetry_snapshot

    snap = telemetry_snapshot()
    rank, world = peek_rank(), peek_world_size()
    rec = {"rank": rank, "stats": snap["stats"],
           "hists": snap.get("hists") or {}}
    seq = next(_snap_seq)
    if world <= 1:
        view = merge_records([rec])
        view["stragglers"] = straggler_report([rec])
        return view
    from ..distributed import store_collective as _sc

    store = _sc.get_store(timeout)
    key = f"__fleet_snap__/{seq}/{rank}"
    store.put(key, json.dumps(rec), ttl=max(60, int(timeout) * 4))
    if rank != 0:
        return None
    prefix = f"__fleet_snap__/{seq}/"
    deadline = time.monotonic() + float(timeout)
    while True:
        items = store.list(prefix)
        if len(items) >= world:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"fleet_snapshot: {len(items)}/{world} rank "
                f"records after {timeout}s — is every rank calling "
                "fleet_snapshot()?")
        time.sleep(0.05)
    records = []
    for k, v in sorted(items.items()):  # list() returns {key: value}
        try:
            records.append(json.loads(v))
        except ValueError:
            _cmon.stat_add("monitor/fleet/bad_records", 1)
        try:  # best-effort cleanup; the ttl reaps leftovers
            store.delete(k)
        except Exception:
            pass
    view = merge_records(records)
    view["stragglers"] = straggler_report(records)
    return view
