"""paddle_tpu.monitor.sanitize — runtime sanitizer core (PTA04x/06x).

The last several review cycles kept catching the same three bug
classes by hand: host references into donated XLA buffers (a
zero-copy `np.asarray` snapshot view mutated by the next dispatch's
donation; a stale donated buffer fed back into a program), background-
thread lock/teardown races (watchdog vs wedged writer, daemon threads
racing interpreter exit), and hand-written sharding layouts that only
fail at dispatch time. This module is the RUNTIME half of turning
those review catches into machine-checked invariants; the static
passes live in `paddle_tpu.analysis.{donation,sharding,concurrency}`
and both halves report through the analysis Finding/Report machinery
(`analysis/<code>/findings` counters, PTA04x/05x/06x codes).

Families (PADDLE_SANITIZE, `,`/`;`-separated, chaos-style grammar):

    donation    use-after-donate detection: every donating dispatch
                registers its donated buffers + dispatch site; a
                deleted buffer showing up as a later input raises a
                PTA041 report naming BOTH sites instead of the opaque
                XLA "buffer has been deleted" crash. Also verifies
                snapshot hostification owns its memory (PTA043
                `owndata` check at the elastic._hostify boundary).
    locks       instrumented lock wrappers (monitor/flight, elastic
                checkpointing, io, the metrics exporter): cross-thread
                lock-acquisition-order graph with cycle detection
                (PTA060), timed holds flagging blocking work under a
                lock (PTA061, `locks:hold_ms=` threshold), and an
                at-exit census of non-daemon threads still alive
                (PTA063).
    sharding    arms the PTA05x sharding-spec lints in
                DistributedTrainStepCompiler to RAISE on
                error-severity findings before compile (under plain
                PADDLE_ANALYSIS=1 they only report).
    serving     KV-block accounting in the serving engine
                (inference.serving.kv_cache): double-free /
                foreign-free of a pool block reports PTA071 at the
                faulting call, the allocator's
                `audit_leaks(live)` / `LLMEngine.check_drained()`
                report PTA070 for blocks still owned by requests
                the engine no longer tracks, and refcount/COW
                violations over prefix-cache-shared blocks (a block
                physically reclaimed while other requests still map
                it, or a shared block mutated without copy-on-write)
                report PTA074 at the faulting call.
    numerics    precision sanitizer (PTA09x): the TrainStepCompiler
                fuses a per-tensor absmax/absmin/nonfinite stats
                probe over loss/grads/params (host-read every
                `sample=N`th dispatch, saturation threshold
                `absmax=T`) and the build-time precision audits —
                fp16 master-weightless training (PTA093), fp16
                autocast of range-sensitive ops (PTA092) — RAISE.
    all / 1     every family.

    e.g.  PADDLE_SANITIZE=donation;locks:hold_ms=250
          PADDLE_SANITIZE=numerics:sample=10:absmax=30000

Zero-overhead contract (the chaos `_armed` pattern): with nothing
armed every hook gates on a module-attribute boolean
(`sanitize._donation`, `sanitize._locks`, `sanitize._sharding`) and
`lock()`/`condition()` hand back plain threading primitives — no
wrapper, no counters. bench.py embeds `extra.sanitize` and asserts
the disarmed path leaves ZERO sanitize/analysis-PTA counters behind.

Like PADDLE_CHAOS, the env spec arms at import; module-level locks in
adopting modules are only instrumented when the family is armed at
their creation (process start). Objects constructed after a
programmatic `configure()` (tests) are instrumented too.
"""
from __future__ import annotations

import atexit
import os
import re
import sys
import threading
import time
import weakref
from collections import OrderedDict, deque

import numpy as np

from ..core import monitor as _cmon

__all__ = [
    "FAMILIES", "PARAMS", "configure", "disarm", "armed", "families",
    "describe", "parse_spec", "note_donated", "check_args",
    "explain_deleted", "verify_owned", "verify_host_tree", "SanLock",
    "lock", "condition", "lock_order_edges", "check_lock_order",
    "thread_census", "findings", "clear_findings",
    "flush_flight_events",
]

FAMILIES = {
    "donation": "use-after-donate detection + snapshot owndata checks "
                "(PTA041/PTA043)",
    "locks": "lock-order deadlock analysis, timed holds, thread-leak "
             "census (PTA060/PTA061/PTA063)",
    "sharding": "strict mode for the PTA05x sharding-spec lints "
                "(errors raise before compile)",
    "serving": "KV-block leak/double-free + prefix-cache refcount/COW "
               "accounting in the serving engine "
               "(PTA070/PTA071/PTA074)",
    "compress": "quantized-collective invariants: error-feedback "
                "residual never donated (PTA080), quantized "
                "allreduce on a non-SUM op / integer dtype "
                "(PTA081) — error findings raise",
    "numerics": "precision sanitizer (PTA09x): TrainStepCompiler "
                "fuses a per-tensor absmax/absmin/nonfinite stats "
                "probe over loss/grads/params and the build-time "
                "precision audits (fp16 master-weightless training, "
                "fp16 autocast of range-sensitive ops) raise",
}

PARAMS = {
    "hold_ms": "locks: flag a lock held longer than this many "
               "milliseconds (PTA061; default 1000)",
    "sample": "numerics: host-readback cadence — observe the fused "
              "stats every Nth dispatch (default "
              "$PADDLE_NUMERICS_SAMPLE or 1)",
    "absmax": "numerics: saturation threshold — |x| above this "
              "reports PTA092 (default $PADDLE_NUMERICS_ABSMAX or "
              "0.9*65504)",
}

# hot-path gates — one module-attribute read per call site
_armed = False
_donation = False
_locks = False
_sharding = False
_serving = False
_compress = False
_numerics = False
_spec = ""
_opts: dict = {}


def _flight():
    """Lazy flight import: flight.py adopts our lock wrappers at its
    own import, so this module must not import it back at top."""
    from . import flight

    return flight


# events recorded while flight.py is still mid-import (the env
# autostart arms from INSIDE flight's own `from . import sanitize`) —
# buffered and replayed by flush_flight_events() at the end of
# flight's import so the sanitize_arm event dump bundles promise is
# kept on the primary arming path
_pending_events: list = []


def _record_event(kind, **data):
    try:
        fl = _flight()
        rec = getattr(fl, "record", None)
        if rec is None:  # flight mid-import: record not defined yet
            raise AttributeError("flight mid-import")
        rec(kind, **data)
    except Exception:
        if len(_pending_events) < 16:
            _pending_events.append((kind, data))


def flush_flight_events():
    """Replay events buffered before the flight recorder existed.
    Called by monitor.flight at the end of its own module import."""
    while _pending_events:
        kind, data = _pending_events.pop(0)
        try:
            _flight().record(kind, **data)
        except Exception:
            return


# ---------------------------------------------------------------------------
# findings plumbing (shared by every family)
# ---------------------------------------------------------------------------

_findings: deque = deque(maxlen=256)
_finding_keys: set = set()  # rate-limit: one report per distinct key
_state_lock = threading.Lock()  # guards _findings/_finding_keys only


def _emit(code, message, file=None, line=None, dedup=None):
    """One runtime finding: analysis/<code>/findings + sanitize
    counters, a flight event (dump bundles show sanitizer hits), a
    stderr line, and a bounded in-memory record for findings()/tests.
    `dedup` suppresses repeat reports of the same condition (counters
    still tick) so a hot loop can't flood stderr."""
    _cmon.stat_add(f"analysis/{code}/findings", 1)
    _cmon.stat_add("sanitize/findings", 1)
    if dedup is not None:
        with _state_lock:
            if dedup in _finding_keys:
                return None
            _finding_keys.add(dedup)
    _record_event("sanitize_finding", code=code,
                  message=str(message)[:200])
    entry = {"code": code, "message": message, "file": file,
             "line": line}
    with _state_lock:
        _findings.append(entry)
    try:
        where = f" ({file}:{line})" if file else ""
        print(f"[paddle_tpu.sanitize] {code}: {message}{where}",
              file=sys.stderr)
    except Exception:
        pass
    return entry


def findings():
    """Accumulated runtime findings as analysis Finding objects."""
    from ..analysis.diagnostics import Finding

    with _state_lock:
        snap = list(_findings)
    return [Finding(e["code"], e["message"], file=e["file"],
                    line=e["line"], analyzer="sanitize")
            for e in snap]


def clear_findings():
    with _state_lock:
        _findings.clear()
        _finding_keys.clear()


def _site(skip=1):
    """file:line of the caller outside this module — the cheapest
    useful anchor (sys._getframe walk, no traceback formatting)."""
    try:
        f = sys._getframe(skip)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:
            return "<unknown>"
        return f"{f.f_code.co_filename}:{f.f_lineno}"
    except Exception:
        return "<unknown>"


# ---------------------------------------------------------------------------
# spec / arming
# ---------------------------------------------------------------------------

def parse_spec(spec):
    """`family[:param=value]*[;,]...` -> {family: {param: float}}.
    `all`/`1`/`on`/`true` arm every family. Raises ValueError on
    unknown families/params (the chaos-spec contract: loud, never
    silently misarmed)."""
    fams: dict = {}
    for part in re.split(r"[;,]", str(spec)):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        name = fields[0].strip().lower()
        params = {}
        for field in fields[1:]:
            if "=" not in field:
                raise ValueError(
                    f"sanitize param {field!r} in {part!r} is not "
                    "key=value")
            k, v = field.split("=", 1)
            k = k.strip()
            if k not in PARAMS:
                raise ValueError(
                    f"unknown sanitize param {k!r} (known: "
                    f"{', '.join(sorted(PARAMS))})")
            try:
                params[k] = float(v)
            except ValueError:
                raise ValueError(
                    f"bad sanitize param value {v!r} for {k} in "
                    f"{part!r}")
        if name in ("all", "1", "on", "true"):
            for f in FAMILIES:
                fams.setdefault(f, {}).update(params)
        elif name in FAMILIES:
            fams.setdefault(name, {}).update(params)
        else:
            raise ValueError(
                f"unknown sanitize family {name!r} (known: "
                f"{', '.join(sorted(FAMILIES))}, all)")
    return fams


def configure(spec=None):
    """Arm the families a spec describes (default: $PADDLE_SANITIZE).
    Replaces any previous configuration; empty/unset disarms. Returns
    the armed {family: params} map."""
    global _armed, _donation, _locks, _sharding, _serving, \
        _compress, _numerics, _spec, _opts
    if spec is None:
        spec = os.environ.get("PADDLE_SANITIZE", "")
    fams = parse_spec(spec) if spec else {}
    _opts = fams
    _donation = "donation" in fams
    _locks = "locks" in fams
    _sharding = "sharding" in fams
    _serving = "serving" in fams
    _compress = "compress" in fams
    _numerics = "numerics" in fams
    _armed = bool(fams)
    _spec = str(spec) if fams else ""
    if fams:
        _cmon.stat_set("sanitize/armed", len(fams))
        for f in fams:
            _cmon.stat_add(f"sanitize/{f}/armed", 1)
        _record_event("sanitize_arm", spec=_spec,
                      families=sorted(fams))
        try:
            _cmon.VLOG(0, f"sanitize: armed {sorted(fams)} ({_spec})")
        except Exception:
            pass
        if _locks:
            _register_atexit_census()
    return fams


def disarm():
    global _armed, _donation, _locks, _sharding, _serving, \
        _compress, _numerics, _spec, _opts
    _armed = _donation = _locks = _sharding = _serving = \
        _compress = _numerics = False
    _spec = ""
    _opts = {}
    # zero the gauge only if arming ever created it — stat_get/set
    # would CREATE a sanitize/armed=0 stat and dirty the "disarmed
    # runs leave zero sanitize counters" bench contract
    if "sanitize/armed" in _cmon.registry._stats:
        _cmon.stat_set("sanitize/armed", 0)


def armed(family=None):
    return _armed if family is None else family in _opts


def families():
    return sorted(_opts)


def describe():
    """Small JSON-able state summary — embedded in flight dump
    bundles so a post-mortem shows what the sanitizers were watching
    when the incident hit."""
    with _donated_lock:
        n_donated = len(_donated)
    with _edge_lock:
        n_edges = len(_edges)
    with _state_lock:
        n_findings = len(_findings)
    return {"spec": _spec, "families": families(),
            "findings": n_findings, "donations_tracked": n_donated,
            "lock_edges": n_edges}


# ---------------------------------------------------------------------------
# PTA04x — donation sanitizer
# ---------------------------------------------------------------------------

# id(array) -> (weakref|None, donating site, seq). Bounded: a long run
# donates the same param/slot buffers over and over; old generations
# get garbage-collected and their weakrefs die, so eviction is safe.
_donated: OrderedDict = OrderedDict()
_donated_lock = threading.Lock()
_DONATED_MAX = 4096
_donate_seq = 0


def _iter_array_leaves(obj):
    """Yield jax-array-like leaves (duck-typed on is_deleted/delete so
    this module never imports jax) of nested dict/list/tuple trees."""
    if isinstance(obj, dict):
        for v in obj.values():
            yield from _iter_array_leaves(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _iter_array_leaves(v)
    elif obj is not None and hasattr(obj, "is_deleted") \
            and hasattr(obj, "delete"):
        yield obj


def note_donated(trees, site=None):
    """Register every jax-array leaf of `trees` as donated by the
    dispatch at `site`. Call AFTER the donating dispatch with the OLD
    (pre-replacement) values — exactly the buffers XLA just freed or
    reused. Cheap: an id()-keyed dict insert per leaf."""
    global _donate_seq
    site = site or _site()
    with _donated_lock:
        _donate_seq += 1
        seq = _donate_seq
        for leaf in _iter_array_leaves(trees):
            try:
                wr = weakref.ref(leaf)
            except TypeError:
                wr = None
            _donated[id(leaf)] = (wr, site, seq)
            _cmon.stat_add("sanitize/donation/tracked", 1)
        while len(_donated) > _DONATED_MAX:
            _donated.popitem(last=False)


def _donation_of(leaf):
    with _donated_lock:
        ent = _donated.get(id(leaf))
    if ent is None:
        return None
    wr, site, seq = ent
    if wr is not None and wr() is not leaf:
        return None  # id reuse — not the array we registered
    return site, seq


def check_args(trees, site=None):
    """Scan dispatch inputs for already-deleted (donated) buffers and
    convert the imminent opaque XLA "buffer has been deleted" crash
    into a PTA041 report naming the donating dispatch AND this use.
    Raises RuntimeError on the first hit."""
    site = site or _site()
    for leaf in _iter_array_leaves(trees):
        try:
            dead = leaf.is_deleted()
        except Exception:
            continue
        if not dead:
            continue
        don = _donation_of(leaf)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if don is not None:
            msg = (f"use-after-donate: array shape={shape} was "
                   f"donated by dispatch at {don[0]} (donation "
                   f"#{don[1]}) and is used again at {site} — the "
                   "caller kept a reference to a buffer the donating "
                   "program freed/reused (adopt the sibling's live "
                   "state, or re-fetch the updated value)")
        else:
            msg = (f"use of a deleted jax buffer shape={shape} at "
                   f"{site} (deleted outside any tracked donating "
                   "dispatch)")
        _emit("PTA041", msg)
        raise RuntimeError(f"PTA041 {msg}")
    return None


def explain_deleted(exc, site=None):
    """Given an exception whose message smells like jax's deleted-
    buffer crash, build the PTA041-annotated replacement (or None if
    it isn't one). Callers `raise explain_deleted(e) from e` to keep
    the original traceback."""
    text = str(exc)
    if "deleted" not in text.lower() and "donat" not in text.lower():
        return None
    site = site or _site()
    with _donated_lock:
        last = next(reversed(_donated.values())) if _donated else None
    hint = (f"; newest tracked donation was at {last[1]}"
            if last else "")
    msg = (f"use-after-donate at {site}: {text}{hint} — a host "
           "reference into a donated buffer outlived its dispatch")
    _emit("PTA041", msg)
    return RuntimeError(f"PTA041 {msg}")


def verify_owned(arr, site=None, what="host snapshot"):
    """PTA043 owndata check at a hostification boundary: a numpy
    array that does NOT own its memory (`np.asarray` of a CPU jax
    array is a zero-copy view of the live device buffer) is exactly
    the PR-6 bug — the next dispatch's donation mutates the
    "snapshot" in place. Reports and returns an OWNED copy so the
    caller self-heals."""
    if not isinstance(arr, np.ndarray):
        return arr
    if arr.base is None and arr.flags["OWNDATA"]:
        return arr
    site = site or _site()
    _emit("PTA043",
          f"{what} does not own its memory (owndata="
          f"{bool(arr.flags['OWNDATA'])}, base="
          f"{type(arr.base).__name__}) at {site} — a zero-copy view "
          "of a live device buffer would be mutated by the next "
          "donating dispatch; taking an owned copy",
          dedup=f"PTA043:{site}:{what}")
    _cmon.stat_add("sanitize/donation/unowned_snapshots", 1)
    return np.array(arr)


def verify_host_tree(tree, site=None, what="host snapshot"):
    """verify_owned over every ndarray leaf of a nested snapshot
    tree (the elastic._hostify boundary). Rebuilds containers only
    when armed — the disarmed path never calls this."""
    site = site or _site()
    if isinstance(tree, np.ndarray):
        return verify_owned(tree, site=site, what=what)
    if isinstance(tree, dict):
        return {k: verify_host_tree(v, site=site, what=what)
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(verify_host_tree(v, site=site, what=what)
                          for v in tree)
    return tree


# ---------------------------------------------------------------------------
# PTA06x — concurrency sanitizer
# ---------------------------------------------------------------------------

_tls = threading.local()

# (holder_name, acquired_name) -> {"sites": (site_a, site_b),
#                                  "count": n}
_edges: dict = {}
_edge_lock = threading.Lock()
_hold_reported: set = set()


def _held():
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _hold_ms_threshold():
    return float(_opts.get("locks", {}).get("hold_ms", 1000.0))


class SanLock:
    """Instrumented drop-in for threading.Lock: records the
    cross-thread lock-acquisition-order graph (PTA060 cycle
    detection), times holds (PTA061 blocking-work-under-lock), and
    otherwise delegates. `with`-statement, bare acquire/release and
    Condition(lock=SanLock(...)) all work (Condition's _is_owned
    fallback only needs acquire/release)."""

    __slots__ = ("name", "_lk")

    def __init__(self, name, lk=None):
        self.name = name
        self._lk = lk if lk is not None else threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        got = self._lk.acquire(blocking, timeout)
        if got and _locks:
            site = _site(2)
            held = _held()
            for other, _t0, osite in held:
                if other.name != self.name:
                    _note_edge(other.name, self.name, osite, site)
            held.append((self, time.monotonic(), site))
        return got

    def release(self):
        long_hold = None
        if _locks:
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self:
                    _obj, t0, site = held.pop(i)
                    dur_ms = (time.monotonic() - t0) * 1e3
                    thr = _hold_ms_threshold()
                    if dur_ms > thr:
                        long_hold = (dur_ms, thr, site)
                    break
        self._lk.release()
        if long_hold is not None:
            # emit strictly AFTER releasing: _emit records a flight
            # event, and when THIS lock is the flight ring lock the
            # recorder would re-acquire it — emitting while still
            # held self-deadlocks the exact process being watched
            dur_ms, thr, site = long_hold
            _cmon.stat_add("sanitize/locks/long_holds", 1)
            _emit("PTA061",
                  f"lock '{self.name}' held {dur_ms:.0f} ms "
                  f"(> {thr:.0f} ms threshold) — blocking work "
                  f"under a lock starves every other waiter "
                  f"(acquired at {site})",
                  dedup=f"PTA061:{self.name}")

    def locked(self):
        return self._lk.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<SanLock {self.name} locked={self._lk.locked()}>"


def lock(name):
    """Lock factory the runtime adopts (flight, elastic, io, the
    exporter): a SanLock when the locks family is armed at creation,
    else a plain threading.Lock — the disarmed hot path pays
    nothing."""
    return SanLock(name) if _locks else threading.Lock()


def condition(name):
    """Condition factory: instrumented underlying lock when armed.
    Condition.wait() releases/reacquires through the SanLock, so
    waiting never counts as holding."""
    return (threading.Condition(SanLock(name)) if _locks
            else threading.Condition())


def _note_edge(a, b, site_a, site_b):
    key = (a, b)
    with _edge_lock:
        ent = _edges.get(key)
        if ent is None:
            _edges[key] = {"sites": (site_a, site_b), "count": 1}
            _cmon.stat_add("sanitize/locks/edges", 1)
        else:
            ent["count"] += 1


def lock_order_edges():
    with _edge_lock:
        return {k: dict(v) for k, v in _edges.items()}


def _find_cycles(adj):
    """Simple-cycle enumeration over a small digraph: DFS with a path
    stack, cycles canonicalized (rotated to their min node) so each
    is reported once."""
    cycles = set()

    def dfs(node, path, on_path):
        for nxt in adj.get(node, ()):
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                k = cyc.index(min(cyc))
                cycles.add(tuple(cyc[k:] + cyc[:k]))
            elif len(path) < 16:
                dfs(nxt, path + [nxt], on_path | {nxt})

    for start in list(adj):
        dfs(start, [start], {start})
    return sorted(cycles)


def check_lock_order(report=None, emit=True):
    """Cycle-check the recorded acquisition-order graph: a cycle
    A->B / B->A means two threads can each hold the lock the other
    wants — the watchdog-vs-wedged-writer class of deadlock, caught
    from the ORDERS alone without ever deadlocking. Returns an
    analysis Report of PTA060 findings."""
    from ..analysis.diagnostics import Report

    report = report if report is not None else Report()
    edges = lock_order_edges()
    adj: dict = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    for cyc in _find_cycles(adj):
        legs = []
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            sites = edges[(a, b)]["sites"]
            legs.append(f"'{a}'->'{b}' ({sites[0]} then {sites[1]})")
        msg = ("potential deadlock: lock-acquisition-order cycle "
               + "; ".join(legs)
               + " — impose one global order or drop the inner "
                 "lock before blocking")
        report.add("PTA060", msg, analyzer="sanitize")
        if emit:
            _cmon.stat_add("sanitize/locks/cycles", 1)
            _emit("PTA060", msg, dedup=f"PTA060:{cyc}")
    return report


def thread_census(report=None, emit=True):
    """PTA063 thread-leak census: non-daemon, non-main threads still
    alive — each one blocks interpreter exit and (the PR-6 lesson)
    races XLA's static destructors into a SIGABRT. Run after
    close()/shutdown, and automatically at exit when armed."""
    from ..analysis.diagnostics import Report

    report = report if report is not None else Report()
    for t in threading.enumerate():
        if t is threading.main_thread() or t.daemon or not t.is_alive():
            continue
        msg = (f"non-daemon thread '{t.name}' (ident={t.ident}) still "
               "alive — it outlives close() and will race interpreter "
               "teardown; join it before exit")
        report.add("PTA063", msg, analyzer="sanitize")
        if emit:
            _cmon.stat_add("sanitize/locks/leaked_threads", 1)
            _emit("PTA063", msg, dedup=f"PTA063:{t.name}")
    return report


_atexit_registered = False


def _register_atexit_census():
    global _atexit_registered
    if _atexit_registered:
        return
    _atexit_registered = True

    def _at_exit():
        if not _locks:
            return
        try:
            rep = thread_census(emit=False)
            rep = check_lock_order(report=rep, emit=False)
            for f in rep.findings:
                print(f"[paddle_tpu.sanitize] at-exit "
                      f"{f.code}: {f.message}", file=sys.stderr)
        except Exception:
            pass  # never break interpreter exit

    atexit.register(_at_exit)


# env-driven autostart (the chaos pattern): setting PADDLE_SANITIZE is
# enough for any run importing paddle_tpu to arm. A typo'd spec must
# be LOUD but must not break `import paddle_tpu`.
if os.environ.get("PADDLE_SANITIZE"):
    try:
        configure()
    except ValueError as _e:
        _cmon.stat_add("sanitize/spec_errors", 1)
        try:
            _cmon.VLOG(0, f"sanitize: IGNORING invalid PADDLE_SANITIZE "
                          f"spec ({_e})")
        except Exception:
            pass
