"""paddle_tpu.monitor.memory — device-memory observability + OOM
forensics.

The reference framework tracks process-wide GPU memory through
allocator hooks (platform/monitor.h StatValue gpu_mem stats, the
paddle/fluid/memory facade, and paddle.device.cuda.memory_allocated /
max_memory_allocated on top). On TPU the allocator belongs to PJRT, so
this module reads memory three ways instead of hooking allocations:

  * device stats — PJRT `device.memory_stats()` where the backend
    exposes it (TPU does; the CPU client usually doesn't), with a
    fallback that accounts bytes via a `jax.live_arrays()` census.
    Surfaced as `paddle.device.memory_allocated()` /
    `max_memory_allocated()` / `reset_max_memory_allocated()` /
    `memory_stats()` and the monitor gauges
    `mem/{allocated,peak}_bytes` (synced by `telemetry_snapshot()`).

  * live-array census — `live_array_census()` groups every live jax
    array by (shape, dtype) and reports bytes + count per group,
    NEVER array contents. This is the "what is holding HBM" answer a
    RESOURCE_EXHAUSTED post-mortem needs.

  * per-program footprints — jit records each compiled program's
    `memory_analysis()` (argument/output/temp/generated-code bytes)
    through `record_program_memory()`; gauges land under
    `mem/program/<fn>/...` and `jit.cache_report()` carries the same
    numbers into every flight dump bundle.

OOM forensics: `is_oom_error()` classifies XlaRuntimeError
RESOURCE_EXHAUSTED; `oom_observer()` (auto-armed by `hapi.Model.fit`)
writes an "oom" flight bundle whose memory section holds device
stats, per-program footprints and the top-K census before re-raising;
the flight excepthook classifies the same way for uncaught OOMs.

Env knobs: PADDLE_MEM_CENSUS_TOP_K (census groups in reports/dumps,
default 15), PADDLE_MEM_PROGRAM (0 disables per-program
memory_analysis capture at jit build — it costs one extra XLA
backend compile per program), PADDLE_MEM_STEP (0 disables the
per-step StepTimer memory gauges/counters).
"""
from __future__ import annotations

import contextlib
import threading

from ..core import monitor as _cmon
from ..core.place import Place as _Place
from ..core.place import device_of as _place_device_of
from .flight import _env_int, _env_on  # shared env-parsing semantics

__all__ = [
    "memory_allocated", "max_memory_allocated",
    "reset_max_memory_allocated", "memory_stats",
    "live_array_census", "sync_gauges", "record_program_memory",
    "extract_memory_analysis",
    "program_capture_enabled", "step_tracking_enabled",
    "step_reading",
    "program_footprints", "memory_report", "memory_section",
    "is_oom_error", "oom_observer", "auto_oom_observer",
    "census_top_k",
]


def census_top_k():
    """Census groups embedded in reports/dump bundles
    (PADDLE_MEM_CENSUS_TOP_K, default 15; <= 0 means unlimited)."""
    return _env_int("PADDLE_MEM_CENSUS_TOP_K", 15)


def program_capture_enabled():
    """PADDLE_MEM_PROGRAM gate for memory_analysis capture at jit
    build. Default on; the capture costs one extra XLA backend
    compile per program (the lowering is shared, the backend pass is
    not), so huge-model users can switch it off."""
    return _env_on("PADDLE_MEM_PROGRAM", True)


def step_tracking_enabled():
    """PADDLE_MEM_STEP gate for the per-step StepTimer memory gauges
    (a census walk per step on backends without PJRT stats)."""
    return _env_on("PADDLE_MEM_STEP", True)


def step_reading():
    """(allocated, peak) bytes for per-step tracking — the shared
    body of StepTimer.end_step and Profiler.step: one memory_stats()
    walk, (0, 0) when PADDLE_MEM_STEP=0 or the reading fails (a
    half-initialized backend must not break a training step)."""
    if not step_tracking_enabled():
        return 0, 0
    try:
        stats = memory_stats()
        return stats["allocated_bytes"], stats["peak_bytes"]
    except Exception:
        return 0, 0


# ---------------------------------------------------------------------------
# Device stats (PJRT, census fallback) + peak tracking
# ---------------------------------------------------------------------------

_peak_lock = threading.Lock()
# per-device watermarks, keyed by str(resolved device):
# [peak_bytes, reset_seen]. reset_seen=True means PJRT's own
# monotonic peak_bytes_in_use predates the reset, so only locally
# observed values feed that device's watermark from then on.
_peaks = {}


def _observe(key, allocated, pjrt_peak=None):
    """Fold one allocated-bytes observation (plus PJRT's own peak
    when trustworthy) into the device's watermark."""
    with _peak_lock:
        ent = _peaks.setdefault(key, [0, False])
        cand = int(allocated)
        if pjrt_peak and not ent[1]:
            cand = max(cand, int(pjrt_peak))
        if cand > ent[0]:
            ent[0] = cand
        return ent[0]


def _census_total(device=None):
    """Total bytes across jax.live_arrays() — the allocated-bytes
    fallback where PJRT exposes no memory stats. With `device`, only
    bytes resident on that device count (per-shard for multi-device
    arrays), so a forced multi-device host (e.g.
    --xla_force_host_platform_device_count=N) gets real per-device
    numbers instead of N copies of the process-global total."""
    import jax

    total = 0
    for a in jax.live_arrays():
        try:
            if device is None:
                total += int(a.nbytes)
                continue
            devs = a.devices()
            if device not in devs:
                continue
            if len(devs) == 1:
                total += int(a.nbytes)
            else:
                total += sum(int(s.data.nbytes)
                             for s in a.addressable_shards
                             if s.device == device)
        except Exception:
            pass  # an array mid-deletion must not kill accounting
    return total


def _resolve_device(device):
    """Resolve a reference-API device specifier — None, an ordinal
    int, a Place, or a "tpu:0"/"gpu:1"/"cpu"-style string — to a
    jax Device,
    so memory_allocated(0) or memory_allocated("tpu:0") reads the
    real device instead of silently accounting nothing against a
    bogus string-keyed watermark. jax Devices pass through."""
    import jax

    if device is None:
        return jax.devices()[0]
    if isinstance(device, bool):
        raise TypeError(f"invalid device specifier: {device!r}")
    if isinstance(device, _Place):
        # the package's own Place objects (what get_device_place()
        # returns) resolve through the device-context pool so the
        # accounted device is the SAME one tensor placement uses —
        # including its fallback (TPUPlace on a CPU-only host reads
        # the device eager tensors actually land on, not an error)
        return _place_device_of(device)
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):
        plat, _, idx = device.partition(":")
        if plat.isdigit() and not idx:
            return jax.devices()[int(plat)]
        # honor the platform leg: "cpu" on a TPU host must read the
        # host client, not silently alias devices()[0] (jax raises
        # on a platform the process has no client for — a clear
        # error beats bytes from the wrong device)
        devs = jax.devices(plat) if plat else jax.devices()
        return devs[int(idx) if idx else 0]
    return device


def _read(device):
    """One reading: (watermark key, allocated bytes, PJRT peak or
    None, raw PJRT stat dict, source). Resolves device=None (and
    int/string specifiers) to a jax Device up front so explicit
    jax.devices()[0], "tpu:0", 0 and None share one watermark."""
    dev = _resolve_device(device)
    raw = _cmon.device_memory_stats(dev)
    if raw.get("bytes_in_use") is not None:
        return (str(dev), int(raw["bytes_in_use"]),
                raw.get("peak_bytes_in_use"), raw, "pjrt")
    return str(dev), _census_total(dev), None, raw, "census"


def memory_allocated(device=None):
    """Bytes currently allocated on the device (reference:
    paddle.device.cuda.memory_allocated). PJRT `bytes_in_use` where
    available, else the live-array census total."""
    key, used, pjrt_peak, _, _ = _read(device)
    _observe(key, used, pjrt_peak)
    return used


def max_memory_allocated(device=None):
    """High-water mark of allocated bytes since process start or the
    last reset_max_memory_allocated() (reference:
    paddle.device.cuda.max_memory_allocated). Per device. Seeded
    from PJRT's peak_bytes_in_use until a reset; after a reset it
    tracks the max of values observed by this module (PJRT peaks are
    monotonic and cannot be reset from the client)."""
    key, used, pjrt_peak, _, _ = _read(device)
    return _observe(key, used, pjrt_peak)


def reset_max_memory_allocated(device=None):
    """Reset the device's tracked high-water mark to its CURRENT
    allocated bytes (reference:
    paddle.device.cuda.reset_max_memory_allocated). Returns the new
    watermark."""
    key, used, _, _, _ = _read(device)
    with _peak_lock:
        _peaks[key] = [used, True]
        return used


def memory_stats(device=None):
    """Full device-memory stat dict: the raw PJRT stats (when the
    backend has them) plus the normalized keys every backend gets —
    `allocated_bytes`, `peak_bytes` (this module's resettable
    watermark) and `source` ("pjrt" | "census"). One reading — use
    this (not allocated+max back to back) on hot paths: the census
    fallback walks every live array per reading."""
    key, used, pjrt_peak, raw, source = _read(device)
    peak = _observe(key, used, pjrt_peak)
    out = dict(raw) if source == "pjrt" else {}
    out.update({"source": source, "allocated_bytes": used,
                "peak_bytes": peak})
    return out


def sync_gauges():
    """Push the device memory numbers into the StatRegistry
    (mem/allocated_bytes, mem/peak_bytes) — called by
    monitor.telemetry_snapshot() so exporter flushes, bench records
    and dump bundles always carry fresh values."""
    stats = memory_stats()
    used, peak = stats["allocated_bytes"], stats["peak_bytes"]
    _cmon.stat_set("mem/allocated_bytes", used)
    _cmon.stat_set("mem/peak_bytes", peak)
    return used, peak


# ---------------------------------------------------------------------------
# Live-array census
# ---------------------------------------------------------------------------

def live_array_census(top_k=None):
    """Group every live jax array by (shape, dtype): bytes + count
    per group, sorted by bytes descending — never array CONTENTS.
    `top_k` caps the group list (None -> PADDLE_MEM_CENSUS_TOP_K;
    <= 0 -> unlimited). Totals always cover every live array, so a
    truncated report still accounts all bytes."""
    import jax

    if top_k is None:
        top_k = census_top_k()
    groups = {}
    total_bytes = 0
    total_arrays = 0
    for a in jax.live_arrays():
        try:
            key = (tuple(a.shape), str(a.dtype))
            nbytes = int(a.nbytes)
        except Exception:
            continue  # mid-deletion array
        total_arrays += 1
        total_bytes += nbytes
        ent = groups.get(key)
        if ent is None:
            groups[key] = [1, nbytes]
        else:
            ent[0] += 1
            ent[1] += nbytes
    ranked = sorted(groups.items(), key=lambda kv: -kv[1][1])
    n_groups = len(ranked)
    if top_k and top_k > 0:
        ranked = ranked[:top_k]
    return {
        "total_bytes": total_bytes,
        "total_arrays": total_arrays,
        "group_count": n_groups,
        "truncated": n_groups > len(ranked),
        "groups": [{"shape": list(shape), "dtype": dtype,
                    "count": cnt, "bytes": nbytes}
                   for (shape, dtype), (cnt, nbytes) in ranked],
    }


# ---------------------------------------------------------------------------
# Per-program footprints (fed by jit at build time)
# ---------------------------------------------------------------------------

_MEM_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def extract_memory_analysis(compiled):
    """`compiled.memory_analysis()` as a plain byte dict (None when
    the backend exposes no analysis). `compiled` is a
    jax.stages.Compiled (or anything with .memory_analysis())."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for key, attr in _MEM_FIELDS:
        try:
            out[key] = int(getattr(ma, attr))
        except (AttributeError, TypeError):
            out[key] = 0
    # XLA's own peak-usage identity: arguments + outputs + temps +
    # generated code, minus buffers aliased into the arguments
    out["total_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                          + out["temp_bytes"]
                          + out["generated_code_bytes"]
                          - out["alias_bytes"])
    return out


def record_program_memory(name, compiled):
    """extract_memory_analysis() plus the `mem/program/<name>/...`
    gauge writes — what the jit build path calls per fresh cache
    entry."""
    out = extract_memory_analysis(compiled)
    if out is None:
        return None
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "generated_code_bytes", "total_bytes"):
        _cmon.stat_set(f"mem/program/{name}/{key}", out[key])
    return out


def program_footprints(report=None):
    """Per-program memory analyses off the live jit caches (the same
    numbers jit.cache_report() embeds) — {name: byte dict}. Pass a
    precomputed cache_report() list as `report` to skip the live-
    compiler walk (dump bundles already hold one for jit_caches)."""
    if report is None:
        try:
            from .. import jit as _jit

            report = _jit.cache_report()
        except Exception:
            return {}
    out = {}

    def _put(name, m):
        # two live compilers can share kind:fn (e.g. the fused and
        # tail train_step siblings over one model class) — suffix
        # instead of overwriting so neither footprint is dropped
        key, n = name, 2
        while key in out:
            key = f"{name}({n})"
            n += 1
        out[key] = m

    for ent in report:
        mem = ent.get("memory")
        if not mem:
            continue
        name = f"{ent.get('kind')}:{ent.get('fn')}"
        if isinstance(mem, list):
            for i, m in enumerate(mem):
                if m:
                    # entry 0 keeps the plain name — same ordinal
                    # scheme as the mem/program/<fn>[#N]/* gauges, so
                    # bundle footprints and exporter gauges match by
                    # name
                    _put(name if i == 0 else f"{name}#{i}", m)
        else:
            _put(name, mem)
    return out


# ---------------------------------------------------------------------------
# Reports / dump-bundle section
# ---------------------------------------------------------------------------

def memory_report(top_k=None):
    """The full live picture: device stats + per-program footprints +
    the live-array census. What `python -m paddle_tpu.monitor memory`
    prints and what an OOM bundle embeds. Degrades to
    {"uninitialized": True} before any jax backend is live — this is
    an evidence-gathering path (the /memz handler thread, pre-init
    REPL hooks) and must never be the thing that initializes a
    backend."""
    from . import flight as _flight

    if not _flight._jax_backends_live():
        return {"uninitialized": True}
    return {"device": memory_stats(),
            "programs": program_footprints(),
            "census": live_array_census(top_k)}


def memory_section(census=True, jit_report=None):
    """The `memory` key of a flight dump bundle. Census is included
    for OOM/live-inspection dumps; watchdog/crash bundles keep the
    cheap device + program half only unless asked. `jit_report`
    forwards a precomputed cache_report() to program_footprints()."""
    try:
        out = {"device": memory_stats(),
               "programs": program_footprints(jit_report)}
        if census:
            out["census"] = live_array_census()
        return out
    except Exception as e:  # forensics must never break the dump
        return {"error": f"{type(e).__name__}: {e}"}


# ---------------------------------------------------------------------------
# OOM classification + observer
# ---------------------------------------------------------------------------

def is_oom_error(exc):
    """True when `exc` is the XLA runtime's RESOURCE_EXHAUSTED (the
    HBM-exhaustion crash on TPU). Classified by type NAME + message —
    jaxlib moves XlaRuntimeError between modules across versions, and
    message matching keeps `Out of memory` variants (BFC allocator
    text) classified even if the canonical code string changes."""
    if exc is None:
        return False
    name = type(exc).__name__
    if name not in ("XlaRuntimeError", "JaxRuntimeError"):
        return False
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg


@contextlib.contextmanager
def oom_observer(reason="oom"):
    """Context manager that turns a RESOURCE_EXHAUSTED crash into a
    forensics bundle WITH the memory section (device stats, per-
    program footprints, top-K live-array census — taken while the
    arrays that caused the OOM are still live), then re-raises.
    Auto-armed around the `hapi.Model.fit` train loop; the flight
    excepthook skips re-dumping an exception this observer already
    bundled."""
    try:
        yield
    except Exception as e:
        if is_oom_error(e) and not getattr(
                e, "_paddle_flight_dumped", False):
            try:
                from . import flight as _flight
                import sys

                _flight.record("oom", message=str(e)[:300])
                # write_dump builds the memory section itself;
                # full_memory=True keeps the census (taken HERE,
                # while the offending arrays are still live) even
                # when the caller renamed the reason
                _flight.write_dump(
                    reason, full_memory=True,
                    extra={"exception": _flight._format_exception(
                        *sys.exc_info())})
                try:
                    e._paddle_flight_dumped = True
                except Exception:
                    pass
            except Exception:
                pass  # forensics must not mask the original OOM
        raise


def auto_oom_observer():
    """What `hapi.Model.fit` wraps the train loop in: oom_observer()
    unless the operator explicitly disabled flight auto-arming
    (PADDLE_FLIGHT_AUTOARM set falsy — the same off switch
    flight.maybe_auto_arm honors). Unlike maybe_auto_arm's unset
    default (distributed runs only), OOM bundles default ON even
    single-host: an OOM is exactly the failure a notebook user wants
    evidence for, and the observer costs nothing until one fires.
    Explicit oom_observer() calls are never gated."""
    if _env_on("PADDLE_FLIGHT_AUTOARM", True):
        return oom_observer()
    return contextlib.nullcontext()
