"""paddle_tpu.monitor — unified runtime telemetry hub.

Four pieces (reference: platform/monitor.h StatRegistry + STAT_ADD,
platform/profiler/ RecordEvent instrumentation, and the stat-export
tooling around them):

  * process-wide counters — re-exported from core.monitor (stat_add /
    stat_set / registry / device_memory_stats ...), populated by the
    instrumented layers: `op/...` (engine dispatch under
    FLAGS_profile_ops), `jit/...` (compile cache hits/misses + wall
    time, digest-cache evictions, and the latency-hiding pipeline's
    `jit/{dispatches,steps,steps_per_dispatch}` — program launches vs
    train steps covered), `comm/...` (per-collective calls/bytes/host
    time), `io/...` (dataloader batches/bytes/ring waits, plus the
    device-feed stage's `io/h2d_us` and
    `io/device_prefetch/{depth,stalls,bytes}`), `step/...` (train-loop
    metrics via StepTimer), `analysis/...` (paddle_tpu.analysis:
    checks run, `analysis/<PTA code>/findings` per diagnostic,
    hook_errors), and `serve/...` (the inference.serving engine:
    requests/tokens/prefill_us/decode_us/evictions, the
    `serve/kv_blocks/{used,free}` pool gauges and the
    `serve/queue_depth` admission gauge).

  * StepTimer — per-step training metrics hub: step time, throughput,
    loss, lr and PJRT device-memory high water, written into the
    StatRegistry under `step/...` and mirrored as chrome-trace counter
    (ph "C") samples whenever a Profiler is capturing, so the merged
    host+device timeline shows memory/throughput alongside spans.

  * MetricsExporter — periodic JSON-lines or Prometheus-textfile flush
    of the full registry snapshot. Env-configurable
    (PADDLE_MONITOR_EXPORT_PATH / _INTERVAL / _FORMAT) so long
    benchmark and multi-host runs leave an inspectable metrics trail
    without code changes.

  * flight (submodule) — always-on failure forensics: a bounded ring
    of structured runtime events, a collective/compile watchdog that
    dumps all-thread stacks + the ring tail + a telemetry snapshot
    when a slice wedges, crash/SIGUSR1 dump bundles, and the
    `python -m paddle_tpu.monitor` CLI (inspect / merge-traces /
    tail). See flight.py and the README "Failure forensics" section.

  * chaos (submodule) — deterministic, seeded fault injection over
    named runtime sites (collectives, store rendezvous, checkpoint
    writes, DataLoader fetches, compiled dispatch), armed by the
    PADDLE_CHAOS spec and observed through chaos/* counters + flight
    events. See chaos.py and the README "Chaos testing & resilience"
    section.

  * server (submodule) — the PULL side (ISSUE 18): an in-process
    debug/metrics HTTP server (`monitor.serve(port=0)`, env-armed by
    PADDLE_MONITOR_SERVE from Model.fit / the serving Router) whose
    /metrics page shares prometheus_text() with the exporter, plus
    live /statusz /flightz /memz /perfz /tracez pages and /profilez
    on-demand capture; `python -m paddle_tpu.monitor scrape` pulls N
    ranks' pages into the fleet straggler report. See server.py and
    the README "Live introspection" section.

  * alerts (submodule) — the ACTING side (ISSUE 20): declarative SLO
    alert rules (threshold / windowed-quantile / rate / burn_rate /
    fraction / absence) over the live registry, armed by the
    PADDLE_ALERTS spec, evaluated on a bounded cadence into
    pending→firing→resolved state with alerts/* counters, flight
    events, the /alertz page, fleet-wide rollup, and the serving
    Autoscaler as first closed-loop consumer. See alerts.py and the
    README "Alerting & autoscaling" section.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time

from ..core.monitor import (  # noqa: F401 — the counter surface
    StatValue, StatRegistry, Histogram, registry, stat_add, stat_get,
    stat_set, stat_reset, hist_observe, hist_get, snapshot_quantile,
    VLOG, vlog_level, device_memory_stats, device_memory_in_use,
)
from . import flight  # noqa: E402 — the failure-forensics leg
from . import memory  # noqa: E402 — the device-memory leg
from . import perf  # noqa: E402 — the compute/roofline leg (ISSUE 16)
from . import chaos  # noqa: E402 — deterministic fault injection
from . import sanitize  # noqa: E402 — runtime sanitizer core (ISSUE 10)
from . import trace  # noqa: E402 — per-request serving traces (ISSUE 15)
from . import fleet  # noqa: E402 — fleet aggregation + stragglers
from . import server  # noqa: E402 — live introspection plane (ISSUE 18)
from . import alerts  # noqa: E402 — SLO alert rules + burn rate (ISSUE 20)
from .server import (  # noqa: F401 — the pull-side lifecycle surface
    serve, get_server, stop_server, maybe_auto_serve,
)

__all__ = [
    "StatValue", "StatRegistry", "Histogram", "registry", "stat_add",
    "stat_get", "stat_set", "stat_reset", "hist_observe", "hist_get",
    "snapshot_quantile", "VLOG", "vlog_level",
    "device_memory_stats", "device_memory_in_use", "StepTimer",
    "MetricsExporter", "start_exporter", "stop_exporter",
    "get_exporter", "telemetry_snapshot", "fleet_snapshot",
    "prometheus_text", "serve", "get_server", "stop_server",
    "maybe_auto_serve", "flight",
    "memory", "perf", "chaos", "trace", "fleet", "server", "alerts",
]


def telemetry_snapshot():
    """Timestamped copy of the full StatRegistry — the record the
    exporter flushes and bench.py embeds in its `extra` field. Syncs
    the flight ring's amortized counters and the device-memory
    gauges (mem/{allocated,peak}_bytes) first so both are exact in
    every flush/dump."""
    flight.sync_stats()
    try:
        # guard like flight's own evidence gathering: a snapshot
        # taken from a crash/watchdog dump path must neither break
        # on nor INITIALIZE a backend mid-rendezvous (jax.devices()
        # blocks rather than raises there)
        if flight._jax_backends_live():
            memory.sync_gauges()
    except Exception:
        pass
    return {"ts": round(time.time(), 3), "rank": _rank(),
            "stats": registry.snapshot(),
            # histogram summaries travel BESIDE the flat int stats
            # (ISSUE 15): sparse bucket maps + exact sum/count/min/max
            # per Histogram, each internally consistent
            "hists": registry.snapshot_histograms()}


def fleet_snapshot(timeout=60.0):
    """Live fleet-wide merge of every rank's telemetry_snapshot() over
    the rank-0 KV-store bootstrap (see monitor/fleet.py): rank 0
    returns the merged view (counters summed, gauges per-rank,
    histograms bucket-merged, stragglers flagged), other ranks return
    None. Single-process: the local snapshot as a one-rank view."""
    return fleet.fleet_snapshot(timeout=timeout)


# ONE copy of the launch-env rank parsing, shared with the dump
# bundles (flight.py owns it; drift here would make exporter rank
# labels disagree with dump-file rank labels)
_rank = flight._rank


class StepTimer:
    """Per-step training metrics hub (the train-loop analog of
    STAT_ADD at every layer).

    Usage (hapi.callbacks.Telemetry drives this from Model.fit):

        st = StepTimer()
        st.begin_step()
        ...one train step...
        st.end_step(batch_size=bs, loss=l, lr=lr)

    Every end_step updates the `step/...` registry stats and — when a
    profiler.Profiler is capturing — records counter samples that
    export as chrome-trace ph "C" events."""

    # flight-ring event kind -> step-attribution wall (ISSUE 16): the
    # spans/events the instrumented layers ALREADY leave per step,
    # bucketed into where the wall time went. Whatever the ring
    # doesn't explain is host time (Python, optimizer host math,
    # tracing) — the remainder bucket
    _ATTRIB_KINDS = {
        "dispatch_end": "device", "serve_decode_end": "device",
        "serve_prefill_end": "device", "linalg_end": "device",
        "collective_end": "comm",
        "io_fetch": "io", "io_h2d": "io", "ckpt_write_end": "io",
    }

    def __init__(self, window=100):
        self._t0 = None
        self._wall0 = None   # wall-clock twin of _t0 (ring ts domain)
        self._window = int(window)
        self._times = []     # recent step durations (seconds)
        self._last = {}
        self._mem_prev = None  # allocated bytes at last step boundary

    def begin_step(self):
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        flight.record("step_begin")

    def end_step(self, batch_size=None, loss=None, lr=None):
        now = time.perf_counter()
        if self._t0 is None:
            return None
        dt = now - self._t0
        self._t0 = None
        self._times.append(dt)
        if len(self._times) > self._window:
            del self._times[:len(self._times) - self._window]

        stat_add("step/count", 1)
        stat_add("step/total_time_us", int(dt * 1e6))
        stat_set("step/last_time_us", int(dt * 1e6))
        # the step-time DISTRIBUTION (ISSUE 15): p50/p99 step time is
        # what the fleet straggler detector compares across ranks —
        # the int gauges above only carry last/total
        hist_observe("step/hist/time_us", dt * 1e6)
        throughput = None
        if batch_size:
            stat_add("step/samples", int(batch_size))
            throughput = batch_size / dt if dt > 0 else 0.0
            # gauge kept float: int() would truncate big-model runs
            # under 1 sample/s to a stalled-looking 0
            stat_set("step/throughput", round(throughput, 3))
        import math

        if loss is not None and math.isfinite(float(loss)):
            # micro-units: the registry holds ints (monitor.h int64).
            # A non-finite loss (diverged run, tripped guard) keeps
            # the last finite gauge — int(nan) raises, and crashing
            # the telemetry callback was exactly how a NaN loss used
            # to kill the fit before terminate_on_nan could see it
            stat_set("step/last_loss_e6", int(float(loss) * 1e6))
        if lr is not None and math.isfinite(float(lr)):
            stat_set("step/lr_e9", int(float(lr) * 1e9))
        # step-boundary memory tracking (PADDLE_MEM_STEP=0 disables —
        # on backends without PJRT stats each reading is a live-array
        # census walk): allocated/peak gauges under step/mem/*, the
        # signed per-step delta, and — while a Profiler captures —
        # mem/{allocated,peak}_bytes counter (ph "C") samples so the
        # merged chrome trace shows a memory timeline next to spans
        used, peak = memory.step_reading()
        if used or peak:
            stat_set("step/mem/allocated_bytes", used)
            registry.get("step/mem/peak_bytes").maximum(peak)
            if self._mem_prev is not None:
                stat_set("step/mem/delta_bytes", used - self._mem_prev)
            self._mem_prev = used
            # legacy names (pre-memory-module consumers)
            stat_set("step/device_mem_bytes_in_use", used)
            registry.get("step/device_mem_peak_bytes").maximum(peak)

        # step-time decomposition (ISSUE 16, PADDLE_PERF_STEP=0
        # disables): bucket the flight ring's spans that closed
        # inside this step into device/comm/io walls; the
        # unexplained remainder is host time. Clamped — overlapped
        # walls (a feeder thread's h2d under a device dispatch) can
        # sum past the step, and a decomposition that exceeds 100%
        # reads as nonsense
        if perf.step_attrib_enabled() and self._wall0 is not None:
            attrib = self._step_attrib(int(dt * 1e6))
            if attrib is not None:
                for wall, us in attrib.items():
                    stat_set(f"step/attrib/{wall}_us", us)

        from .. import profiler as _prof

        if _prof.is_recording():
            _prof.record_counter("step_time_ms", dt * 1e3, ts=now)
            if throughput is not None:
                _prof.record_counter("throughput", throughput, ts=now)
            if loss is not None:
                _prof.record_counter("loss", float(loss), ts=now)
            if lr is not None:
                _prof.record_counter("lr", float(lr), ts=now)
            if used or peak:
                _prof.record_counter("mem/allocated_bytes", used,
                                     ts=now)
                _prof.record_counter("mem/peak_bytes", peak, ts=now)
                _prof.record_counter("device_mem_bytes_in_use", used,
                                     ts=now)
        self._last = {"time_s": dt, "batch_size": batch_size,
                      "loss": loss, "lr": lr}
        flight.record("step_end", us=int(dt * 1e6),
                      batch_size=batch_size,
                      loss=None if loss is None else float(loss))
        return dt

    def _step_attrib(self, dt_us):
        """{device, comm, io, host} µs for the step that just ended,
        from the ring events stamped since begin_step. Best effort:
        a cleared/disabled ring yields None (no gauges written — a
        zeroed decomposition would read as an all-host step)."""
        buckets = {"device": 0, "comm": 0, "io": 0}
        saw = False
        try:
            for ev in flight.recorder.tail(512):
                if ev.get("ts", 0.0) < self._wall0:
                    continue
                saw = True
                wall = self._ATTRIB_KINDS.get(ev.get("kind"))
                if wall is None:
                    continue
                buckets[wall] += int(ev.get("dur_us")
                                     or ev.get("us") or 0)
        except Exception:
            return None
        if not saw:
            # not even our own step_begin event → ring off/cleared
            return None
        known = sum(buckets.values())
        if dt_us > 0 and known > dt_us:
            scale = dt_us / known
            for wall in buckets:
                buckets[wall] = int(buckets[wall] * scale)
            buckets["host"] = 0
        else:
            buckets["host"] = max(0, dt_us - known)
        return buckets

    def summary(self):
        n = len(self._times)
        avg = sum(self._times) / n if n else 0.0
        out = {"steps_windowed": n, "avg_step_ms": avg * 1e3}
        bs = self._last.get("batch_size")
        if bs and avg > 0:
            out["avg_throughput"] = bs / avg
        out.update({k: v for k, v in self._last.items()
                    if v is not None})
        return out


# ---------------------------------------------------------------------------
# Prometheus exposition (ONE renderer: exporter textfile + /metrics)
# ---------------------------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")

# the series suffixes a Prometheus histogram family OWNS — a scalar
# whose sanitized name lands on `<hist>_bucket`/`_sum`/`_count` would
# alias the histogram's own series just as hard as a same-name scalar
_PROM_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _prom_name(name):
    return "paddle_tpu_" + _PROM_BAD.sub("_", name)


def _prom_escape(v):
    """Prometheus label-value escaping (backslash, double quote,
    newline — the exposition-format contract). ONE escaper for every
    label either leg of the renderer emits, so user-supplied names
    riding a label can never produce an unparsable or aliasing
    line."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_value(v):
    """One sample value, Prometheus-spelled: bools as 0/1, non-finite
    floats as NaN/+Inf/-Inf (valid exposition tokens — `nan`/`inf`
    Python spellings are not), everything else as-is."""
    import math

    if isinstance(v, bool):
        return "1" if v else "0"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(v)


def _prom_resolve(stat_names, hist_names):
    """Final metric base name for every input, computed over the
    UNION of both families. The `_` substitution is lossy
    (`step/time` and `step_time` both sanitize to
    `paddle_tpu_step_time`), so when several names land on one metric
    name EVERY collider gets a suffix derived (sha1) from its
    ORIGINAL name — no two stats, no stat-vs-histogram pair, and no
    stat-vs-`_bucket`/`_sum`/`_count` pair ever alias one Prometheus
    series. The suffix itself is a pure function of the name; WHETHER
    a name needs one depends on the name set in the snapshot, which
    only grows within a process (stat_reset zeroes, never removes)
    and is identical across ranks running the same code — so series
    names stay stable except at the moment a brand-new collider first
    registers. Returns {("stat"|"hist", original_name): metric}."""
    import hashlib

    keys = [("stat", k) for k in stat_names] \
        + [("hist", k) for k in hist_names]
    sanitized = {key: _prom_name(key[1]) for key in keys}
    counts = {}
    for m in sanitized.values():
        counts[m] = counts.get(m, 0) + 1
    hist_series = {sanitized[key] + suf for key in keys
                   if key[0] == "hist"
                   for suf in _PROM_HIST_SUFFIXES}
    out = {}
    for key in keys:
        m = sanitized[key]
        if counts[m] > 1 or (key[0] == "stat" and m in hist_series):
            m = f"{m}_{hashlib.sha1(key[1].encode()).hexdigest()[:6]}"
        out[key] = m
    return out


def _prom_render(items, hists):
    """The full exposition: scalar lines for (name, value) pairs plus
    classic histogram families — `<name>_bucket{le=...}` cumulative
    series with `_sum`/`_count`, one `le` per OCCUPIED bucket's upper
    edge (sparse inputs stay sparse on the wire; cumulative semantics
    make skipped empty buckets exactly equivalent) and the mandatory
    `+Inf` terminal. Overflow observations only appear in `+Inf`, as
    they exceed every finite boundary. ONE renderer — the
    MetricsExporter `.prom` textfile and the debug server's /metrics
    page both call this, so the two surfaces can never disagree on a
    series name."""
    names = _prom_resolve([k for k, _ in items], hists)
    lines = []
    for k, v in items:
        lines.append(f"{names[('stat', k)]} {_prom_value(v)}")
    for name in sorted(hists):
        s = hists[name]
        m = names[("hist", name)]
        lo = float(s["lo"])
        pd = int(s["per_decade"])
        nb = pd * int(s["decades"])
        buckets = sorted((int(k), int(v))
                         for k, v in (s.get("buckets") or {}).items())
        cum = 0
        for idx, c in buckets:
            cum += c
            if idx > nb:
                continue  # overflow folds into +Inf below
            le = lo * 10.0 ** (idx / pd) if idx else lo
            lines.append(
                f'{m}_bucket{{le="{_prom_escape(f"{le:.6g}")}"}} '
                f'{cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {int(s["count"])}')
        lines.append(f'{m}_sum {float(s["sum"]):.6g}')
        lines.append(f'{m}_count {int(s["count"])}')
    return lines


def prometheus_text(snap=None):
    """Prometheus exposition text for a telemetry snapshot (the live
    one when None) — the single formatter behind both pull
    (`/metrics` on the debug server) and push (the exporter's `.prom`
    textfile), per the one-renderer discipline."""
    if snap is None:
        snap = telemetry_snapshot()
    items = sorted((snap.get("stats") or {}).items())
    items.append(("export_timestamp_seconds", snap.get("ts", 0)))
    lines = _prom_render(items, snap.get("hists") or {})
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Metrics exporter
# ---------------------------------------------------------------------------


class MetricsExporter:
    """Periodic flush of the StatRegistry snapshot to a file.

    fmt="jsonl" (default): append one JSON line per flush —
        {"ts": ..., "rank": ..., "stats": {...}}
    fmt="prom": atomically rewrite a Prometheus textfile (the
        node-exporter textfile-collector contract: write tmp, rename).

    A `{rank}` placeholder in the path expands to the trainer rank so
    multi-host runs don't clobber one file — resolved at FLUSH time,
    not construction: the env autostart runs at import, before a
    jax-native multi-host launch knows its rank (expanding then would
    send every host to `..._0...`). The background thread is a
    daemon; stop() joins it and performs one final flush."""

    def __init__(self, path, interval=30.0, fmt=None):
        self._path_template = str(path)
        self.interval = float(interval)
        if fmt is None:
            fmt = "prom" if self._path_template.endswith(".prom") \
                else "jsonl"
        if fmt not in ("jsonl", "prom"):
            raise ValueError(
                f"MetricsExporter: unknown format {fmt!r} "
                "(expected 'jsonl' or 'prom')")
        self.fmt = fmt
        self._stop = threading.Event()
        self._thread = None
        self._errors_seen = set()

    @property
    def path(self):
        return self._path_template.replace("{rank}", str(_rank()))

    def flush(self):
        snap = telemetry_snapshot()
        path = self.path  # one {rank} resolution per flush
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if self.fmt == "jsonl":
            with open(path, "a") as f:
                f.write(json.dumps(snap) + "\n")
        else:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(prometheus_text(snap))
            os.replace(tmp, path)
        return snap

    def _note_flush_error(self, exc):
        """Background-flush failure accounting: an unwritable path on
        a week-long run must be VISIBLE, not a bare `pass` — count
        every failure under monitor/export/errors (the exporter may
        recover and flush it later; bench.py embeds it either way) and
        VLOG each DISTINCT error once so the log isn't flooded at
        every interval."""
        stat_add("monitor/export/errors", 1)
        key = f"{type(exc).__name__}: {exc}"
        if key not in self._errors_seen:
            self._errors_seen.add(key)
            try:
                VLOG(0, f"MetricsExporter: flush to {self.path} "
                        f"failed ({key}); will keep retrying")
            except Exception:
                # a broken stderr raising INSIDE the error handler
                # would kill the exporter thread — the exact silent
                # death this method exists to prevent
                pass

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.flush()
            except Exception as e:
                # an unwritable path OR an unserializable stat value
                # must not silently kill the exporter thread for the
                # rest of a long run — keep trying; direct flush()
                # callers still see the raise
                self._note_flush_error(e)

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle-metrics-exporter",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, flush=True):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if flush:
            try:
                self.flush()
            except Exception as e:
                self._note_flush_error(e)


_exporter = None
_exporter_lock = sanitize.lock("monitor.exporter")


def get_exporter():
    return _exporter


def start_exporter(path=None, interval=None, fmt=None):
    """Start (or return) the process-wide exporter. With no arguments
    the env contract applies: PADDLE_MONITOR_EXPORT_PATH (required —
    returns None when unset), PADDLE_MONITOR_EXPORT_INTERVAL (seconds,
    default 30), PADDLE_MONITOR_EXPORT_FORMAT (jsonl|prom, default by
    extension)."""
    global _exporter
    path = path or os.environ.get("PADDLE_MONITOR_EXPORT_PATH")
    if not path:
        return None
    if interval is None:
        try:
            interval = float(os.environ.get(
                "PADDLE_MONITOR_EXPORT_INTERVAL", "30"))
        except ValueError:
            interval = 30.0
    fmt = fmt or os.environ.get("PADDLE_MONITOR_EXPORT_FORMAT") or None
    with _exporter_lock:
        # construct (and so validate fmt/path) BEFORE stopping the
        # running exporter — a typo'd format must not kill the live
        # metrics trail and leave a dead object registered
        new = MetricsExporter(path, interval, fmt)
        if _exporter is not None:
            _exporter.stop(flush=False)
        _exporter = new.start()
        return _exporter


def stop_exporter(flush=True):
    global _exporter
    with _exporter_lock:
        e, _exporter = _exporter, None
    if e is not None:
        e.stop(flush=flush)


# env-driven autostart: setting PADDLE_MONITOR_EXPORT_PATH is enough
# for any run importing paddle_tpu to leave a metrics trail
if os.environ.get("PADDLE_MONITOR_EXPORT_PATH"):
    try:
        start_exporter()
    except Exception:
        pass
