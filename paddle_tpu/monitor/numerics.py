"""paddle_tpu.monitor.numerics — runtime numerics probe (PTA09x).

The runtime half of the precision sanitizer (static half:
`analysis/precision.py`). Under `PADDLE_SANITIZE=numerics[:sample=N]
[:absmax=T]` the TrainStepCompiler fuses a per-tensor stats reduction
— absmax over finite values, smallest nonzero magnitude, non-finite
count — over loss/grads/params into the compiled step (riding the
same build hook as `guard_nonfinite`, so the DISARMED lowering is
bit-identical: the probe slot is an empty pytree that adds zero
outputs). Every Nth dispatch the host reads the tiny packed stats
and feeds:

  * gauges    numerics/<tree>/absmax, .../absmin_nonzero
  * counters  numerics/<tree>/saturated, .../nonfinite
  * histogram numerics/hist/absmax (distribution over observations)
  * findings  PTA092 via sanitize._emit — `sanitize_finding` flight
    events name the OFFENDING TENSOR, so an overflow in a dump
    bundle is attributable to `grad/linear.w`, not just a skipped
    step; GradScaler growth/backoff events annotate the same
    timeline

Params (spec or env): `sample=N` host-readback cadence (default
$PADDLE_NUMERICS_SAMPLE or 1 — the device-side stats are fused and
cheap; sampling bounds only the host sync), `absmax=T` saturation
threshold (default $PADDLE_NUMERICS_ABSMAX or 0.9*65504, fp16's
ceiling with headroom).

Dispatch-time findings REPORT (counters + flight + stderr), they
never raise — aborting mid-training belongs to guard_nonfinite;
build-time audits (PTA093 master-weightless fp16) are the raising
half, in analysis/precision.py.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np

from ..core import monitor as _cmon
from . import sanitize as _sanitize

__all__ = ["armed", "sample_every", "absmax_threshold", "stats_tree",
           "observe", "describe", "clear"]

_FP16_MAX = 65504.0

# last host-read stats per tensor, bounded — dump-bundle section
_last: OrderedDict = OrderedDict()
_LAST_MAX = 256
_n_observed = 0
_lock = threading.Lock()


def armed():
    """Hot-path gate (one module-attribute read, the house rule)."""
    return _sanitize._numerics


def _param(name, env, default):
    opts = _sanitize._opts.get("numerics", {})
    if name in opts:
        return float(opts[name])
    raw = os.environ.get(env, "")
    try:
        return float(raw) if raw else float(default)
    except ValueError:
        return float(default)


def sample_every():
    """Host-readback cadence: observe() syncs every Nth call."""
    return max(1, int(_param("sample", "PADDLE_NUMERICS_SAMPLE", 1)))


def absmax_threshold():
    """|x| above this reports saturation risk (PTA092)."""
    return _param("absmax", "PADDLE_NUMERICS_ABSMAX",
                  0.9 * _FP16_MAX)


def stats_tree(tree):
    """TRACED: fuse a (3,)-f32 stats vector per floating leaf of a
    nested dict/list/tuple tree — [absmax over finite values,
    smallest nonzero finite magnitude (0 if none), non-finite
    count]. Returns {joined/path: (3,) array}; empty and non-float
    leaves are skipped so the probe never perturbs dtypes."""
    import jax.numpy as jnp

    out = {}

    def leaf(path, x):
        if not hasattr(x, "dtype") or np.size(x) == 0 \
                or not jnp.issubdtype(x.dtype, jnp.floating):
            return
        finite = jnp.isfinite(x)
        a = jnp.abs(x.astype(jnp.float32))
        absmax = jnp.max(jnp.where(finite, a, 0.0))
        pos = jnp.where(finite & (a > 0), a, jnp.inf)
        absmin = jnp.min(pos)
        absmin = jnp.where(jnp.isfinite(absmin), absmin,
                           jnp.float32(0.0))
        nonfinite = jnp.sum(~finite).astype(jnp.float32)
        out[path] = jnp.stack([absmax, absmin, nonfinite])

    def walk(path, obj):
        if isinstance(obj, dict):
            for k in sorted(obj):
                walk(f"{path}/{k}" if path else str(k), obj[k])
        elif isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(f"{path}/{i}" if path else str(i), v)
        elif obj is not None:
            leaf(path, obj)

    walk("", tree)
    return out


def observe(stats, where="train_step", step=0):
    """Host leg: reduce one dispatch's packed stats (leaves may be
    (3,) or scan-stacked (K, 3)) into gauges/counters/findings.
    Applies the `sample=N` cadence internally — callers invoke it
    every dispatch, the sync happens every Nth. Returns the reduced
    {name: (absmax, absmin_nonzero, nonfinite)} dict on sampled
    calls, None on skipped ones."""
    global _n_observed
    if not stats:
        return None
    with _lock:
        _n_observed += 1
        n = _n_observed
    if (n - 1) % sample_every():
        return None
    thr = absmax_threshold()
    reduced = {}
    for name, v in stats.items():
        arr = np.asarray(v, np.float32).reshape(-1, 3)
        absmax = float(arr[:, 0].max())
        mins = arr[:, 1][arr[:, 1] > 0]
        absmin = float(mins.min()) if mins.size else 0.0
        nonfinite = int(arr[:, 2].sum())
        reduced[name] = (absmax, absmin, nonfinite)
        _cmon.stat_set(f"numerics/{name}/absmax",
                       int(np.ceil(absmax)))
        _cmon.hist_observe("numerics/hist/absmax", absmax)
        if nonfinite:
            _cmon.stat_add(f"numerics/{name}/nonfinite", nonfinite)
            _sanitize._emit(
                "PTA092",
                f"{where} step {step}: {nonfinite} non-finite "
                f"value(s) in tensor '{name}' (absmax of the finite "
                f"part {absmax:.6g}) — the overflow originates HERE, "
                "not merely in the skipped step",
                dedup=f"numerics:nonfinite:{name}")
        elif absmax > thr:
            _cmon.stat_add(f"numerics/{name}/saturated", 1)
            _sanitize._emit(
                "PTA092",
                f"{where} step {step}: tensor '{name}' absmax "
                f"{absmax:.6g} exceeds the saturation threshold "
                f"{thr:.6g} — headed for fp16 overflow (max "
                f"{_FP16_MAX:g}); rescale or keep it in f32",
                dedup=f"numerics:saturated:{name}")
    with _lock:
        for name, vals in reduced.items():
            _last[name] = {"absmax": vals[0],
                           "absmin_nonzero": vals[1],
                           "nonfinite": vals[2], "step": int(step)}
            _last.move_to_end(name)
        while len(_last) > _LAST_MAX:
            _last.popitem(last=False)
    return reduced


def describe():
    """JSON-able snapshot for flight dump bundles: what the probe was
    watching and the freshest per-tensor stats when the incident
    hit."""
    with _lock:
        last = {k: dict(v) for k, v in _last.items()}
        n = _n_observed
    return {"armed": bool(_sanitize._numerics),
            "sample": sample_every() if _sanitize._numerics else None,
            "absmax_threshold": (absmax_threshold()
                                 if _sanitize._numerics else None),
            "observations": n, "last": last}


def clear():
    """Reset observation state (tests)."""
    global _n_observed
    with _lock:
        _last.clear()
        _n_observed = 0
