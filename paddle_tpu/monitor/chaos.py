"""paddle_tpu.monitor.chaos — deterministic, seeded fault injection.

Production TPU fleets treat injected failure as a first-class test
input (the Gemma-on-TPU production comparison, PAPERS.md arxiv
2605.25645): every retry/timeout/degradation decision in the runtime
must be exercised deliberately in CI, not discovered in an incident.
This module is the harness — NAMED INJECTION SITES threaded through
the runtime's failure-prone seams, armed by a spec string and observed
through the same telemetry stack (PR 1/3/5) that watches real faults.

Sites (see SITES; `python -m paddle_tpu.monitor chaos` lists them):

    collective   eager collective enter (distributed.collective.*)
    store_get    TCP-store rendezvous read (store_collective._wait_get)
    store_put    TCP-store rendezvous write (StoreGroupComm puts)
    rendezvous   get_store() bootstrap connect
    ckpt_write   checkpoint snapshot write (incubate.checkpoint.elastic)
    cache_write  persistent compile-cache entry write (jit.persistent_cache)
    io_fetch     DataLoader sample fetch (mp worker loop + in-process)
    dispatch     compiled train-step dispatch (jit.TrainStepCompiler)
    serve_admit  serving-scheduler request admission
    serve_decode serving-engine decode dispatch (LLMEngine)
    serve_route  serving-router replica selection (Router)
    serve_drain  serving-engine graceful drain (LLMEngine.drain)
    serve_spec_verify  speculative-decode draft verification (LLMEngine)

Spec grammar (PADDLE_CHAOS, `;`-separated rules):

    site:fault[:param=value]*
    e.g.  collective:stall:p=0.01:seed=7;ckpt_write:enospc:after=3

Faults (FAULTS) and params (PARAMS) below. Determinism: every rule
owns a `random.Random(seed)` (seed defaults to crc32 of
site:fault:rank), and the after/every/times counters are plain
per-process counts — the SAME spec in the SAME process replays the
SAME fault sequence, which is what lets a chaos regression test assert
exact outcomes.

Zero-overhead contract: with nothing armed, `_armed` is False and
every call site guards with `if chaos._armed: chaos.hit(...)` — one
module-attribute read on the hot path, no spec parsing, no dict walk.

Observability: configuring counts each rule under
`chaos/<site>/<fault>/armed` (+ a `chaos/armed` gauge of live rules)
and records a `chaos_arm` flight event; every trigger counts
`chaos/<site>/<fault>/triggered` and records a `chaos_inject` event,
so watchdog/crash dump bundles show exactly what was injected and the
exporter/bench `chaos/*` counters prove a run was (or was not)
chaos-free.

Programmatic use (tests):

    with chaos.inject("ckpt_write", "enospc", after=1):
        ...
"""
from __future__ import annotations

import contextlib
import errno
import os
import random
import threading
import time
import zlib

from ..core import monitor as _cmon
from . import flight as _flight

__all__ = [
    "SITES", "FAULTS", "PARAMS", "Rule", "parse_spec", "configure",
    "disarm", "inject", "hit", "rules", "active", "ChaosInjected",
    "ChaosBadSample", "XlaRuntimeError",
]

SITES = {
    "collective": "eager collective enter (distributed.collective.*)",
    "store_get": "TCP-store rendezvous read "
                 "(store_collective._wait_get)",
    "store_put": "TCP-store rendezvous write (StoreGroupComm puts)",
    "rendezvous": "get_store() bootstrap connect",
    "ckpt_write": "checkpoint snapshot write "
                  "(incubate.checkpoint.elastic._write_snapshot)",
    "cache_write": "persistent compile-cache entry write "
                   "(jit.persistent_cache._write_entry)",
    "io_fetch": "DataLoader sample fetch (mp worker loop + "
                "single-process _fetch)",
    "dispatch": "compiled train-step dispatch "
                "(jit.TrainStepCompiler._run_compiled)",
    "serve_admit": "serving-scheduler request admission "
                   "(inference.serving.scheduler — delay = slow "
                   "client)",
    "serve_decode": "serving-engine decode dispatch "
                    "(inference.serving.engine; resource_exhausted "
                    "drives the mid-decode eviction path)",
    "serve_route": "serving-router replica selection "
                   "(inference.serving.router — raise = routing "
                   "layer failure before any replica is touched)",
    "serve_drain": "serving-engine graceful drain entry "
                   "(inference.serving.engine.drain — raise = drain "
                   "aborted before any request is exported)",
    "serve_spec_verify": "speculative-decode draft verification "
                         "(inference.serving.engine — corrupt forces "
                         "every draft to diverge; acceptance degrades "
                         "to 1 token/round, emitted tokens stay "
                         "identical)",
    "linalg_dispatch": "distributed linear-algebra program dispatch "
                       "(linalg.dist.runtime.dispatch — SUMMA/"
                       "factorization/eigensolver programs)",
    "comm_compress": "quantized-allreduce build "
                     "(distributed.compress.allreduce — fires at "
                     "trace time like every in-trace collective; "
                     "bitflip corrupts one wire block in the built "
                     "program)",
}

FAULTS = {
    "delay": "sleep ms= milliseconds, then proceed",
    "stall": "sleep secs= seconds (a watchdog-visible hang), then "
             "proceed",
    "hang": "alias of stall",
    "raise": "raise exc= (default ChaosInjected) with msg=",
    "enospc": "raise OSError(ENOSPC) — full checkpoint/log filesystem",
    "torn": "site-interpreted torn write: the site persists a partial "
            "artifact, then raises (ckpt_write, cache_write)",
    "crash": "os._exit(3) THIS process — meant for mp DataLoader "
             "workers",
    "bad_sample": "raise ChaosBadSample — feeds the DataLoader "
                  "on_bad_sample policy",
    "resource_exhausted": "raise a synthetic XlaRuntimeError "
                          "RESOURCE_EXHAUSTED (OOM forensics path)",
    "bitflip": "site-interpreted wire corruption: the quantized "
               "allreduce XORs bit 6 into every code of scale "
               "block 0 (comm_compress)",
    "corrupt": "site-interpreted draft corruption: the serving engine "
               "replaces every speculative draft proposal in the "
               "round, forcing verification to reject them all "
               "(serve_spec_verify)",
}

PARAMS = {
    "p": "trigger probability per eligible call (float, default 1.0; "
         "decisions ride the rule's seeded rng)",
    "seed": "rng seed for p<1 decisions (int, default "
            "crc32('site:fault:rank'))",
    "after": "let the first N calls pass untouched (int, default 0)",
    "every": "of the calls past `after`, arm every Nth (int, "
             "default 1)",
    "times": "maximum triggers (int, default unlimited)",
    "ms": "delay duration in milliseconds (float, default 100)",
    "secs": "stall duration in seconds (float, default 30)",
    "exc": "exception class for `raise`: RuntimeError, OSError, "
           "ValueError, TimeoutError, ConnectionError",
    "msg": "message for `raise`",
}


def _tag(exc):
    """Mark an exception as a RUNTIME fault this module raised (vs
    ChaosBadSample, the bad-RECORD simulation): degradation policies
    like DataLoader's on_bad_sample='skip' must let tagged faults
    propagate, or the chaos/* triggered counters would claim effects
    (an escaping exception) that never happened."""
    try:
        exc._paddle_chaos_fault = True
    except Exception:
        pass
    return exc


class ChaosInjected(RuntimeError):
    """Default exception of the `raise` fault."""


class ChaosBadSample(ValueError):
    """The `bad_sample` fault — what a corrupt record raises."""


class XlaRuntimeError(RuntimeError):
    """Synthetic stand-in for jaxlib's XlaRuntimeError: the NAME is
    what monitor.memory.is_oom_error classifies on, so an injected
    `resource_exhausted` exercises the real OOM forensics path."""


_EXC_NAMES = {
    "RuntimeError": RuntimeError, "OSError": OSError,
    "ValueError": ValueError, "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "ChaosInjected": ChaosInjected,
}

_INT_PARAMS = ("seed", "after", "every", "times")
_FLOAT_PARAMS = ("p", "ms", "secs")

# site-interpreted faults only make sense where a call site enacts
# the returned Rule — arming them elsewhere would count `triggered`
# injections that never happened, corrupting the chaos/* provenance
_SITE_INTERPRETED = {"torn": ("ckpt_write", "cache_write"),
                     "bitflip": ("comm_compress",),
                     "corrupt": ("serve_spec_verify",)}


def _default_seed(site, fault):
    return zlib.crc32(
        f"{site}:{fault}:{_flight._rank()}".encode()) & 0x7FFFFFFF


class Rule:
    """One armed (site, fault) with its trigger discipline. Counters
    (`calls`/`triggers`) and the seeded rng are per-process state —
    forked DataLoader workers inherit a snapshot and count their own
    calls from there."""

    def __init__(self, site, fault, **params):
        if site not in SITES:
            raise ValueError(
                f"unknown chaos site {site!r} (known: "
                f"{', '.join(sorted(SITES))})")
        if fault not in FAULTS:
            raise ValueError(
                f"unknown chaos fault {fault!r} (known: "
                f"{', '.join(sorted(FAULTS))})")
        ok_sites = _SITE_INTERPRETED.get(fault)
        if ok_sites is not None and site not in ok_sites:
            raise ValueError(
                f"chaos fault {fault!r} is site-interpreted and only "
                f"supported at {', '.join(ok_sites)} (got {site!r})")
        self.site = site
        self.fault = "stall" if fault == "hang" else fault
        for k in params:
            if k not in PARAMS:
                raise ValueError(
                    f"unknown chaos param {k!r} in {site}:{fault} "
                    f"(known: {', '.join(sorted(PARAMS))})")
        try:
            self.p = float(params.get("p", 1.0))
            self.seed = int(params.get("seed",
                                       _default_seed(site, fault)))
            self.after = int(params.get("after", 0))
            self.every = max(1, int(params.get("every", 1)))
            self.times = (int(params["times"])
                          if "times" in params else None)
            self.ms = float(params.get("ms", 100.0))
            self.secs = float(params.get("secs", 30.0))
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"bad chaos param value in {site}:{fault}: {e}")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(
                f"chaos param p={self.p} out of [0, 1] in "
                f"{site}:{fault}")
        exc = params.get("exc", "ChaosInjected")
        if exc not in _EXC_NAMES:
            raise ValueError(
                f"unknown chaos exc {exc!r} (known: "
                f"{', '.join(sorted(_EXC_NAMES))})")
        self.exc = _EXC_NAMES[exc]
        self.msg = str(params.get(
            "msg", f"chaos: injected {self.fault} at {site}"))
        self._rng = random.Random(self.seed)
        self.calls = 0
        self.triggers = 0

    def describe(self):
        d = {"site": self.site, "fault": self.fault, "p": self.p,
             "seed": self.seed, "after": self.after,
             "every": self.every, "times": self.times,
             "calls": self.calls, "triggers": self.triggers}
        if self.fault == "delay":
            d["ms"] = self.ms
        if self.fault == "stall":
            d["secs"] = self.secs
        if self.fault == "raise":
            d["exc"] = self.exc.__name__
        return d

    # -- firing ------------------------------------------------------
    def _claim(self):
        """One trigger decision — caller holds the module lock, so
        the calls/triggers counters and the seeded rng advance
        atomically (two threads racing a times=1 rule must not both
        fire, or the 'same spec replays the same fault sequence'
        contract breaks). Returns the claimed trigger ordinal, or
        None."""
        self.calls += 1
        if self.calls <= self.after:
            return None
        if (self.calls - self.after - 1) % self.every:
            return None
        if self.times is not None and self.triggers >= self.times:
            return None
        if self.p < 1.0 and self._rng.random() >= self.p:
            return None
        self.triggers += 1
        return self.triggers

    def _execute(self, site, ctx, n):
        """Record trigger `n` (already claimed under the lock), then
        enact the fault. Returns self for site-interpreted faults
        (`torn`), None otherwise."""
        _cmon.stat_add(f"chaos/{site}/{self.fault}/triggered", 1)
        _flight.record("chaos_inject", site=site, fault=self.fault,
                       n=n, **ctx)
        f = self.fault
        if f == "delay":
            time.sleep(self.ms / 1e3)
            return None
        if f == "stall":
            time.sleep(self.secs)
            return None
        if f == "raise":
            raise _tag(self.exc(self.msg))
        if f == "enospc":
            raise _tag(OSError(
                errno.ENOSPC,
                f"chaos: no space left on device ({site})"))
        if f == "crash":
            # hard worker death (SIGKILL analog a supervisor can't
            # catch) — forked DataLoader workers only: in the trainer
            # process os._exit would bypass the flight excepthook and
            # every emergency-checkpoint path the crash is supposed
            # to exercise, so it downgrades to a raising fault there
            if ctx.get("worker") is None:
                raise _tag(ChaosInjected(
                    f"chaos: crash fault at {site} outside an mp "
                    "worker — raising instead of os._exit"))
            os._exit(3)
        if f == "bad_sample":
            raise ChaosBadSample(
                f"chaos: bad sample injected at {site}")
        if f == "resource_exhausted":
            raise _tag(XlaRuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                f"allocate (chaos injected at {site})"))
        return self  # torn (and future site-interpreted faults)


# site -> [Rule]; _armed is THE hot-path gate (module attribute, read
# by every call site before touching anything else here)
_rules: dict = {}
_armed = False
_spec = ""
_lock = threading.Lock()


def active():
    return _armed


def rules():
    """Flat list of live rules (CLI / tests)."""
    return [r for rs in _rules.values() for r in rs]


def parse_spec(spec):
    """`site:fault[:param=value]*[;...]` -> [Rule]. Raises ValueError
    with an operator-readable message on any unknown
    site/fault/param."""
    out = []
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(
                f"chaos rule {part!r} needs at least site:fault")
        params = {}
        for field in fields[2:]:
            if "=" not in field:
                raise ValueError(
                    f"chaos param {field!r} in {part!r} is not "
                    "key=value")
            k, v = field.split("=", 1)
            params[k.strip()] = v.strip()
        out.append(Rule(fields[0].strip(), fields[1].strip(),
                        **params))
    return out


def _sync_armed_stats():
    _cmon.stat_set("chaos/armed", len(rules()))


def configure(spec=None):
    """Arm the rules a spec describes (default: $PADDLE_CHAOS).
    Replaces any previous configuration; an empty/unset spec disarms.
    Returns the armed rules."""
    global _rules, _armed, _spec
    if spec is None:
        spec = os.environ.get("PADDLE_CHAOS", "")
    parsed = parse_spec(spec) if spec else []
    with _lock:
        _rules = {}
        for r in parsed:
            _rules.setdefault(r.site, []).append(r)
        _armed = bool(parsed)
        _spec = spec if parsed else ""
    _sync_armed_stats()
    if parsed:
        for r in parsed:
            _cmon.stat_add(f"chaos/{r.site}/{r.fault}/armed", 1)
        _flight.record("chaos_arm", spec=spec, rules=len(parsed))
        try:
            _cmon.VLOG(0, f"chaos: armed {len(parsed)} rule(s): "
                          f"{spec}")
        except Exception:
            pass
    return parsed


def disarm():
    global _rules, _armed, _spec
    with _lock:
        _rules = {}
        _armed = False
        _spec = ""
    _sync_armed_stats()


@contextlib.contextmanager
def inject(site, fault, **params):
    """Programmatic injection: arm ONE extra rule for the with-block
    (composes with any spec-armed rules). Yields the Rule so tests can
    read its calls/triggers counters."""
    global _armed
    rule = Rule(site, fault, **params)
    with _lock:
        _rules.setdefault(rule.site, []).append(rule)
        _armed = True
    _cmon.stat_add(f"chaos/{rule.site}/{rule.fault}/armed", 1)
    _sync_armed_stats()
    _flight.record("chaos_arm", site=rule.site, fault=rule.fault,
                   rules=len(rules()))
    try:
        yield rule
    finally:
        with _lock:
            rs = _rules.get(rule.site, [])
            if rule in rs:
                rs.remove(rule)
            if not rs:
                _rules.pop(rule.site, None)
            _armed = bool(_rules)
        _sync_armed_stats()


def hit(site, **ctx):
    """One pass through an injection site. No-op (None) when nothing
    is armed for `site`; otherwise each matching rule gets a trigger
    decision — delays/stalls sleep here, raising faults raise out of
    here, and site-interpreted faults (torn) return their Rule for
    the call site to enact. Call sites guard with
    `if chaos._armed: chaos.hit(...)` so the disarmed path never even
    enters this function."""
    if not _armed:
        return None
    # lock-free pre-check (dict membership is GIL-atomic; arming
    # publishes the site key before _armed flips on configure, and a
    # rare race with inject() just means one extra locked lookup) —
    # sites no armed rule targets stay near zero-overhead even while
    # OTHER sites are armed
    if site not in _rules:
        return None
    with _lock:
        rs = list(_rules.get(site, ()))
    out = None
    for rule in rs:
        with _lock:
            n = rule._claim()
        if n is not None:
            act = rule._execute(site, ctx, n)
            if act is not None:
                out = act
    return out


# env-driven autostart (the exporter pattern): setting PADDLE_CHAOS is
# enough for any run importing paddle_tpu to arm the spec — including
# forked DataLoader workers, which inherit the armed state. A typo'd
# spec must be LOUD but must not break `import paddle_tpu`.
if os.environ.get("PADDLE_CHAOS"):
    try:
        configure()
    except ValueError as _e:
        _cmon.stat_add("chaos/spec_errors", 1)
        try:
            _cmon.VLOG(0, f"chaos: IGNORING invalid PADDLE_CHAOS "
                          f"spec ({_e}) — validate with `python -m "
                          "paddle_tpu.monitor chaos`")
        except Exception:
            pass
