"""paddle_tpu.monitor.perf — compute/roofline observability: the
FLOPs-and-bytes axis of the telemetry stack (the memory module's
compute twin).

The stack could already tell you a request's latency (trace), a
rank's memory (memory) and a hang's stack (flight) — but not where a
step's FLOPs and bytes go: MFU was hand-computed in bench.py from
analytic formulas against a hard-coded v5e peak, and
`compiled.cost_analysis()` was consulted only by the auto-parallel
planner. This module closes that gap three ways:

  * per-program cost ledger — jit records each compiled program's
    `cost_analysis()` (flops, bytes accessed, transcendentals)
    through `record_program_cost()` at every fresh cache entry
    (`StaticFunction`, `TrainStepCompiler` and its distributed
    subclass, the serving decode/prefill programs, `linalg:<label>`
    programs); gauges land under `perf/program/<name>/...` and
    `jit.cache_report()` carries the same numbers ("cost" fields)
    into every flight dump bundle, exactly like the memory ledger.

  * measured attribution — the capture sites observe each program's
    dispatch wall time (blocked on result ready — async dispatch
    would otherwise time the enqueue, not the execution) into
    `jit/hist/<name>/dispatch_us` histograms. Ledger / measurement
    combine in `perf_report()` into achieved FLOP/s, arithmetic
    intensity and per-program MFU against the device-kind peak table
    below, with a roofline verdict per program — compute-bound,
    HBM-bound, or comm-bound (the comm leg priced from the
    `comm/<op>/wire_bytes` counters against the interconnect
    bandwidth). `python -m paddle_tpu.monitor perf` renders the
    table; StepTimer's `step/attrib/{device,host,io,comm}_us`
    decomposition reads the flight ring's spans per step.

  * regression trail — bench.py embeds the ledger + the
    analytic-vs-compiler FLOPs drift ratio as `extra.perf` in every
    record; `benchmarks/regress.py` gates the BENCH_r*.json trail.

Env knobs: PADDLE_PERF_PROGRAM (0 disables cost capture at jit build
— same gating discipline as PADDLE_MEM_PROGRAM; disarmed runs leave
perf/* at zero, the bench-provenance contract), PADDLE_PERF_DISPATCH
(0 disables dispatch wall-time histograms — each observation blocks
on the program's outputs, trading dispatch pipelining for measured
attribution), PADDLE_PERF_STEP (0 disables the StepTimer step-time
decomposition), PADDLE_PEAK_TFLOPS / PADDLE_HBM_GBPS /
PADDLE_ICI_GBPS (peak-table overrides for chips the table doesn't
know).
"""
from __future__ import annotations

from ..core import monitor as _cmon
from ..core.monitor import snapshot_quantile
from . import flight as _flight
from .flight import _env_float, _env_on  # shared env-parsing semantics

__all__ = [
    "program_capture_enabled", "dispatch_timing_enabled",
    "step_attrib_enabled", "extract_cost_analysis",
    "record_program_cost", "observe_dispatch", "program_costs",
    "device_peaks", "roofline_verdict", "perf_report",
    "PEAK_TABLE",
]


def program_capture_enabled():
    """PADDLE_PERF_PROGRAM gate for cost_analysis capture at jit
    build. Default on; the capture rides the SAME extra backend
    compile the memory footprint capture already pays (the compiled
    object is shared), so disabling memory capture alone does not
    save the compile unless this is off too."""
    return _env_on("PADDLE_PERF_PROGRAM", True)


def dispatch_timing_enabled():
    """PADDLE_PERF_DISPATCH gate for per-program dispatch wall-time
    histograms. Each observation blocks on the dispatch's outputs
    (the bench PR-12 discipline — jax dispatch is async and an
    unblocked timer measures the enqueue), which serializes the
    host/device overlap the latency-hiding pipeline buys; 0 restores
    fully async dispatch."""
    return _env_on("PADDLE_PERF_DISPATCH", True)


def step_attrib_enabled():
    """PADDLE_PERF_STEP gate for StepTimer's per-step
    `step/attrib/*` decomposition (a flight-ring tail walk per
    step)."""
    return _env_on("PADDLE_PERF_STEP", True)


# ---------------------------------------------------------------------------
# Per-program cost ledger (fed by the jit/serving/linalg build paths)
# ---------------------------------------------------------------------------

# (ledger key, cost_analysis() key) — XLA spells the byte counter
# with a space
_COST_FIELDS = (
    ("flops", "flops"),
    ("bytes_accessed", "bytes accessed"),
    ("transcendentals", "transcendentals"),
)


def extract_cost_analysis(compiled):
    """`compiled.cost_analysis()` as a plain dict (None when the
    backend exposes no analysis). Normalizes the cross-version shape:
    older jax returns a one-element list of per-computation dicts,
    newer returns the dict directly."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for key, src in _COST_FIELDS:
        try:
            v = float(ca.get(src, 0.0) or 0.0)
        except (TypeError, ValueError):
            v = 0.0
        # XLA reports -1 for "unknown" on some backends — a negative
        # FLOP count would poison every downstream ratio
        out[key] = int(v) if v > 0 else 0
    return out


def record_program_cost(name, compiled):
    """extract_cost_analysis() plus the `perf/program/<name>/...`
    gauge writes — what every capture site calls per fresh compiled
    program. Returns the cost dict (cache_report's "cost" field), or
    None when the backend has no analysis OR capture is disabled —
    callers gate on program_capture_enabled() before paying a
    compile, but this re-check keeps the zero-counter contract even
    for sites that get a compiled object for free."""
    if not program_capture_enabled():
        return None
    out = extract_cost_analysis(compiled)
    if out is None:
        return None
    for key, _ in _COST_FIELDS:
        _cmon.stat_set(f"perf/program/{name}/{key}", out[key])
    return out


def observe_dispatch(name, dur_us):
    """One blocked-on-ready dispatch wall-time observation for
    program `name` — the measured leg the roofline divides the
    ledger's FLOPs by."""
    _cmon.hist_observe(f"jit/hist/{name}/dispatch_us", dur_us)


def program_costs(report=None):
    """Per-program cost analyses off the live jit caches (the same
    numbers jit.cache_report() embeds as "cost") — {name: cost dict}.
    Pass a precomputed cache_report() list as `report` to skip the
    live-compiler walk (dump bundles hold one as jit_caches). The
    naming mirrors memory.program_footprints: kind:fn, "#i" ordinals
    for shape-specialized to_static entries, "(n)" suffixes for
    sibling compilers sharing kind:fn."""
    if report is None:
        try:
            from .. import jit as _jit

            report = _jit.cache_report()
        except Exception:
            return {}
    out = {}

    def _put(name, c):
        key, n = name, 2
        while key in out:
            key = f"{name}({n})"
            n += 1
        out[key] = c

    for ent in report:
        cost = ent.get("cost")
        if not cost:
            continue
        name = f"{ent.get('kind')}:{ent.get('fn')}"
        if isinstance(cost, list):
            for i, c in enumerate(cost):
                if c:
                    _put(name if i == 0 else f"{name}#{i}", c)
        else:
            _put(name, cost)
    return out


# ---------------------------------------------------------------------------
# Device-kind peak table + roofline math
# ---------------------------------------------------------------------------

# kind tag -> (peak dense-bf16 TFLOP/s per chip, HBM GB/s, per-chip
# interconnect GB/s). Published per-chip numbers; the cpu row is a
# deliberately modest stand-in so CPU test runs still get finite
# MFU/verdicts (override via env for a specific host).
PEAK_TABLE = {
    "v4": (275.0, 1228.0, 300.0),
    "v5e": (197.0, 819.0, 200.0),
    "v5p": (459.0, 2765.0, 600.0),
    "v6e": (918.0, 1640.0, 448.0),
    "cpu": (0.2, 50.0, 10.0),
}

# device_kind substrings -> table tag, checked in order (a bare "v5"
# scan would alias v5p and v5e)
_KIND_TAGS = (
    (("v6e", "v6 lite", "trillium"), "v6e"),
    (("v5p",), "v5p"),
    (("v5e", "v5 lite", "v5lite"), "v5e"),
    (("v4",), "v4"),
)


def device_peaks():
    """The roofline ceilings for THIS process's default device:
    {"device_kind", "matched", "peak_tflops", "hbm_gbps",
    "ici_gbps"}. device_kind comes from PJRT; unknown kinds (and the
    CPU client) fall back to the cpu row. PADDLE_PEAK_TFLOPS /
    PADDLE_HBM_GBPS / PADDLE_ICI_GBPS override individual legs —
    both the bench MFU column and the per-program MFU read THIS
    function, so the two can never disagree on the peak."""
    kind = "cpu"
    try:
        # evidence-gathering rule (shared with flight's dump path and
        # the /perfz handler): NEVER initialize a backend just to read
        # its kind — a debug page touching jax.devices() first could
        # pick a platform mid-rendezvous. Uninitialized reads as cpu.
        if _flight._jax_backends_live():
            import jax

            kind = str(getattr(jax.devices()[0], "device_kind", "")
                       or jax.devices()[0].platform)
    except Exception:
        pass
    low = kind.lower()
    matched = "cpu"
    for subs, tag in _KIND_TAGS:
        if any(s in low for s in subs):
            matched = tag
            break
    tf, hbm, ici = PEAK_TABLE[matched]
    return {
        "device_kind": kind,
        "matched": matched,
        "peak_tflops": _env_float("PADDLE_PEAK_TFLOPS", tf),
        "hbm_gbps": _env_float("PADDLE_HBM_GBPS", hbm),
        "ici_gbps": _env_float("PADDLE_ICI_GBPS", ici),
    }


def roofline_verdict(flops, bytes_accessed, peak_tflops, hbm_gbps,
                     comm_frac=0.0):
    """Classify one program against the roofline: "comm-bound" when
    the interconnect leg dominates the measured time (comm_frac >
    0.5 — the fleet's walls are elsewhere), else compare arithmetic
    intensity (flops/byte) with the machine balance
    (peak_flops / hbm_bandwidth): below balance the HBM leg caps the
    program, at/above it the MXUs do."""
    if comm_frac > 0.5:
        return "comm-bound"
    if not flops or not bytes_accessed:
        return "unknown"
    intensity = flops / float(bytes_accessed)
    balance = (peak_tflops * 1e12) / (hbm_gbps * 1e9)
    return "compute-bound" if intensity >= balance else "HBM-bound"


# ---------------------------------------------------------------------------
# The roofline report (CLI `perf`, bench extra.perf)
# ---------------------------------------------------------------------------

def _parse_program_gauges(stats):
    """{name: {flops, bytes_accessed, transcendentals}} out of the
    flat perf/program/<name>/<key> gauge namespace."""
    progs = {}
    prefix = "perf/program/"
    for k, v in (stats or {}).items():
        if not k.startswith(prefix):
            continue
        rest = k[len(prefix):]
        name, _, key = rest.rpartition("/")
        if name and key:
            progs.setdefault(name, {})[key] = v
    return progs


def _dispatch_snap(hists, name):
    """The program's dispatch histogram snapshot — shape-specialized
    `#N` ledger entries share their base name's histogram (one
    distribution per fn, like jit/<fn>/compile_us)."""
    snap = (hists or {}).get(f"jit/hist/{name}/dispatch_us")
    if snap is None and "#" in name:
        snap = (hists or {}).get(
            f"jit/hist/{name.split('#')[0]}/dispatch_us")
    return snap


def perf_report(stats=None, hists=None, peaks=None):
    """The full compute-attribution picture: the peak ceilings, the
    comm leg (wire bytes priced against the interconnect), and per
    program the cost ledger + measured dispatch quantiles + achieved
    FLOP/s, arithmetic intensity, MFU and roofline verdict. Reads
    the LIVE registries by default; pass a dump bundle's
    telemetry["stats"]/["hists"] for offline forensics (the CLI
    `perf <bundle>` path)."""
    if stats is None:
        stats = _cmon.registry.snapshot()
    if hists is None:
        hists = _cmon.registry.snapshot_histograms()
    if peaks is None:
        peaks = device_peaks()
    progs = _parse_program_gauges(stats)
    # total measured dispatch seconds across every program — the
    # denominator the comm leg is weighed against
    total_s = 0.0
    seen_hists = set()
    for name in progs:
        snap = _dispatch_snap(hists, name)
        if snap is not None and id(snap) not in seen_hists:
            seen_hists.add(id(snap))
            total_s += float(snap.get("sum", 0.0)) / 1e6
    wire = sum(v for k, v in (stats or {}).items()
               if k.startswith("comm/") and k.endswith("/wire_bytes"))
    comm_s = wire / (peaks["ici_gbps"] * 1e9) \
        if peaks["ici_gbps"] > 0 else 0.0
    comm_frac = comm_s / total_s if total_s > 0 else 0.0
    out_progs = {}
    for name in sorted(progs):
        cost = progs[name]
        flops = int(cost.get("flops", 0))
        ba = int(cost.get("bytes_accessed", 0))
        ent = {"flops": flops, "bytes_accessed": ba,
               "transcendentals": int(cost.get("transcendentals", 0)),
               "dispatch": None, "achieved_gflops": None,
               "intensity": None, "mfu": None,
               "verdict": roofline_verdict(
                   flops, ba, peaks["peak_tflops"],
                   peaks["hbm_gbps"], comm_frac)}
        if flops and ba:
            ent["intensity"] = round(flops / float(ba), 3)
        snap = _dispatch_snap(hists, name)
        if snap is not None and snap.get("count"):
            p50 = snapshot_quantile(snap, 0.5)
            ent["dispatch"] = {
                "count": int(snap["count"]),
                "p50_us": round(p50, 1),
                "p99_us": round(snapshot_quantile(snap, 0.99), 1),
            }
            if flops and p50 > 0:
                ach = flops / (p50 / 1e6)
                ent["achieved_gflops"] = round(ach / 1e9, 3)
                ent["mfu"] = round(
                    ach / (peaks["peak_tflops"] * 1e12), 4)
        out_progs[name] = ent
    return {
        "peaks": peaks,
        "comm": {"wire_bytes": int(wire),
                 "est_us": int(comm_s * 1e6),
                 "frac": round(comm_frac, 4)},
        "measured_total_us": int(total_s * 1e6),
        "programs": out_progs,
    }
