"""paddle_tpu.monitor.alerts — declarative SLO alerting over the live
StatRegistry (ISSUE 20).

The observability stack so far *records* (counters, histograms,
flight forensics, roofline/memory ledgers) and *exposes* (exporter,
debug server, fleet merge) — nothing acts on any of it. This module
is the third pillar: a rule engine that watches the registry the
instrumented layers already feed and drives a
`pending -> firing -> resolved` state machine per rule, cheap enough
to leave armed in production and OFF by default (zero threads, zero
counters, zero behavior change when disarmed — the house contract).

Rule kinds (KINDS below; `python -m paddle_tpu.monitor alerts`
prints this table):

    threshold   counter/gauge vs bound; the metric may glob
                (`serve/replica/*/healthy:threshold:lt=1` fires when
                ANY replica goes unhealthy)
    quantile    histogram p-quantile vs bound, computed on the
                WINDOWED delta between evaluation ticks
                (Histogram.delta_since) so a week of healthy p99
                cannot mask the last minute's storm
    rate        counter delta per second over a short window
    burn_rate   error-budget consumption, Prometheus multiwindow
                style: fires only when BOTH the short and the long
                window burn faster than `factor`x the budget
    fraction    metric / (metric + of) pool fraction vs bound (KV
                free fraction, cache hit fraction)
    absence     an expected series never appeared

Rules arrive as `AlertRule` objects or a `PADDLE_ALERTS` spec string
in the chaos/sanitize grammar family —
`metric:kind[:param=value]*[;...]`, with the bare words
`serving`/`default`/`all`/`1`/`on`/`true` expanding to the default
serving rule pack (p99 TTFT/ITL, shed rate, queue depth, KV-pool
free fraction, replica-unhealthy persistence). An invalid env spec
is LOUD (VLOG + alerts/spec_errors) but never breaks import.

A background `AlertEvaluator` thread (PADDLE_ALERT_INTERVAL_S
cadence, bounded below at 50ms) calls evaluate_once(): it forces a
flight-ring stat sync FIRST (the ring amortizes its gauges to every
256th event — an evaluator reading stale flight/* gauges would alert
on last minute's truth), snapshots the registry once, and ticks
every rule. Transitions write `alerts/<name>/firing` (gauge 1/0) and
`alerts/<name>/transitions`, record `alert_fire`/`alert_resolve`
flight events, and fan out to registered listeners — the serving
Autoscaler (inference/serving/autoscaler.py) closes the
observability->capacity loop from exactly this callback. Every
flight dump bundle embeds describe() under its "alerts" key, the
debug server serves it at /alertz, and `monitor scrape`/`fleet`
roll per-rank alert states up fleet-wide.
"""
from __future__ import annotations

import fnmatch
import os
import threading
import time

from ..core import monitor as _cmon
from . import flight as _flight
from . import sanitize as _sanitize

__all__ = [
    "KINDS", "PARAMS", "AlertRule", "AlertEvaluator", "parse_spec",
    "default_rules", "configure", "disarm", "armed", "rules",
    "describe", "evaluate_once", "add_listener", "remove_listener",
    "env_interval_s", "OK", "PENDING", "FIRING", "RESOLVED",
]

# rule states
OK = "ok"                # armed, never fired
PENDING = "pending"      # breaching, streak < for
FIRING = "firing"
RESOLVED = "resolved"    # fired at least once, currently clean

KINDS = {
    "threshold": "counter/gauge vs bound (metric may glob: "
                 "serve/replica/*/healthy:threshold:lt=1)",
    "quantile": "histogram p-quantile on the WINDOWED delta between "
                "ticks vs bound (q=0.99 default)",
    "rate": "counter delta per second over `window` vs bound",
    "burn_rate": "error-budget burn (metric=errors, total=requests): "
                 "fires when short AND long windows both burn "
                 ">= factor x budget",
    "fraction": "metric / (metric + of) pool fraction vs bound",
    "absence": "expected series (stat or histogram) never appeared",
}

# param -> help; values parse as float except the *metric-name*
# params (name/total/of), which stay strings
PARAMS = {
    "name": "rule name — counters land under alerts/<name>/*",
    "gt": "fire when value > bound",
    "ge": "fire when value >= bound",
    "lt": "fire when value < bound",
    "le": "fire when value <= bound",
    "q": "quantile in [0, 1] (quantile kind; default 0.99)",
    "for": "consecutive breaching ticks before firing (default 1)",
    "clear": "consecutive clean ticks before resolving (default 2)",
    "min_n": "minimum windowed observations for quantile (default 1)",
    "window": "short window seconds (rate/burn_rate; default 60)",
    "long": "long window seconds (burn_rate; default 3600)",
    "budget": "allowed error fraction (burn_rate; default 0.01)",
    "factor": "burn multiple that fires (burn_rate; default 14.4)",
    "total": "total-counter metric name (burn_rate; required)",
    "of": "complement metric name (fraction; required)",
}

_STR_PARAMS = ("name", "total", "of")
_OPS = {
    "gt": lambda v, b: v > b,
    "ge": lambda v, b: v >= b,
    "lt": lambda v, b: v < b,
    "le": lambda v, b: v <= b,
}
_DEFAULT_WORDS = ("serving", "default", "all", "1", "on", "true")


def env_interval_s():
    """PADDLE_ALERT_INTERVAL_S — evaluator cadence (default 1s,
    bounded below at 50ms: the tick snapshots the whole registry)."""
    return max(0.05, _flight._env_float("PADDLE_ALERT_INTERVAL_S",
                                        1.0))


def _live_hist(name):
    """The live Histogram, or None — WITHOUT get-or-create: an alert
    probing a series that never existed must not conjure an empty
    histogram into /metrics."""
    reg = _cmon.registry
    with reg._lock:
        return reg._hists.get(name)


def _hist_names():
    reg = _cmon.registry
    with reg._lock:
        return list(reg._hists)


class AlertRule:
    """One declarative rule + its live state. Construction validates
    everything (the chaos Rule contract: loud ValueError with an
    operator-readable message, never a silently-misarmed rule)."""

    def __init__(self, metric, kind, **params):
        self.metric = str(metric).strip()
        self.kind = str(kind).strip().lower()
        if not self.metric:
            raise ValueError("alert rule needs a metric name")
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown alert kind {self.kind!r} (known: "
                f"{', '.join(sorted(KINDS))})")
        vals = {}
        for k, v in params.items():
            if k not in PARAMS:
                raise ValueError(
                    f"unknown alert param {k!r} (known: "
                    f"{', '.join(sorted(PARAMS))})")
            if k in _STR_PARAMS:
                vals[k] = str(v).strip()
                continue
            try:
                vals[k] = float(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"bad alert param value {v!r} for {k} in "
                    f"{self.metric}:{self.kind}")
        ops = [k for k in _OPS if k in vals]
        if self.kind in ("burn_rate", "absence"):
            if ops:
                raise ValueError(
                    f"{self.kind} rules take no {'/'.join(ops)} "
                    f"bound ({self.metric})")
            self.op, self.bound = None, None
        else:
            if len(ops) != 1:
                raise ValueError(
                    f"{self.metric}:{self.kind} needs exactly one "
                    "of gt/ge/lt/le")
            self.op = ops[0]
            self.bound = vals[self.op]
        self.q = float(vals.get("q", 0.99))
        if not 0.0 <= self.q <= 1.0:
            raise ValueError(
                f"alert param q={self.q} out of [0, 1] in "
                f"{self.metric}")
        self.for_ticks = max(1, int(vals.get("for", 1)))
        self.clear_ticks = max(1, int(vals.get("clear", 2)))
        self.min_n = max(1, int(vals.get("min_n", 1)))
        self.window_s = max(0.0, float(vals.get("window", 60.0)))
        self.long_s = max(self.window_s,
                          float(vals.get("long", 3600.0)))
        self.budget = float(vals.get("budget", 0.01))
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(
                f"alert param budget={self.budget} out of (0, 1] in "
                f"{self.metric}")
        self.factor = float(vals.get("factor", 14.4))
        self.total = vals.get("total", "")
        self.of = vals.get("of", "")
        if self.kind == "burn_rate" and not self.total:
            raise ValueError(
                f"{self.metric}:burn_rate needs total=<metric>")
        if self.kind == "fraction" and not self.of:
            raise ValueError(
                f"{self.metric}:fraction needs of=<metric>")
        if "*" in self.metric and self.kind not in ("threshold",
                                                    "absence"):
            raise ValueError(
                f"glob metrics only work for threshold/absence "
                f"rules, not {self.metric}:{self.kind}")
        name = vals.get("name") or self.metric.replace(
            "/", "_").replace("*", "any")
        if not all(c.isalnum() or c in "_.-" for c in name):
            raise ValueError(
                f"bad alert rule name {name!r} (alphanumeric and "
                "_.- only — it keys alerts/<name>/* counters)")
        self.name = name
        # live state
        self.state = OK
        self.value = None
        self.streak = 0
        self.clear_streak = 0
        self.fired = 0
        self._prev = None      # quantile: last Histogram.snapshot()
        self._samples = []     # rate/burn_rate: (now, v[, total])

    # -- evaluation --------------------------------------------------
    def _match_values(self, stats):
        """Numeric values of every stat the (possibly glob) metric
        names — [] when the series does not exist yet."""
        if "*" in self.metric:
            keys = fnmatch.filter(stats, self.metric)
        else:
            keys = [self.metric] if self.metric in stats else []
        return [stats[k] for k in keys
                if isinstance(stats[k], (int, float))
                and not isinstance(stats[k], bool)]

    def _windowed(self, now, w):
        """(dt, deltas...) against the newest sample at least `w`
        old — or the oldest on record while the window fills."""
        base = None
        for s in self._samples:
            if now - s[0] >= w:
                base = s
            else:
                break
        if base is None and self._samples:
            base = self._samples[0]
        cur = self._samples[-1] if self._samples else None
        if base is None or cur is None or cur[0] <= base[0]:
            return None
        return (cur[0] - base[0],) + tuple(
            c - b for c, b in zip(cur[1:], base[1:]))

    def _eval(self, stats, now):
        """(value, breach) for this tick; value None = no data (never
        breaches except for `absence`, whose whole point is no
        data)."""
        k = self.kind
        if k == "absence":
            present = bool(self._match_values(stats))
            if not present:
                pat = self.metric
                present = any(fnmatch.fnmatch(h, pat)
                              for h in _hist_names()) \
                    if "*" in pat else _live_hist(pat) is not None
            return (0.0 if present else 1.0), not present
        if k == "threshold":
            vals = [v for v in self._match_values(stats)
                    if _OPS[self.op](v, self.bound)]
            if vals:
                worst = max(vals) if self.op in ("gt", "ge") \
                    else min(vals)
                return worst, True
            allv = self._match_values(stats)
            if not allv:
                return None, False
            return (max(allv) if self.op in ("gt", "ge")
                    else min(allv)), False
        if k == "fraction":
            m, o = stats.get(self.metric), stats.get(self.of)
            if not isinstance(m, (int, float)) \
                    or not isinstance(o, (int, float)) or m + o <= 0:
                return None, False
            v = m / (m + o)
            return v, _OPS[self.op](v, self.bound)
        if k == "quantile":
            h = _live_hist(self.metric)
            if h is None:
                return None, False
            delta = h.delta_since(self._prev)
            self._prev = h.snapshot()
            if int(delta.get("count", 0)) < self.min_n:
                return None, False
            v = _cmon.snapshot_quantile(delta, self.q, empty=None)
            if v is None:
                return None, False
            return v, _OPS[self.op](v, self.bound)
        if k == "rate":
            v = stats.get(self.metric)
            if not isinstance(v, (int, float)):
                return None, False
            if self._samples and v < self._samples[-1][1]:
                self._samples = []        # counter reset — rebase
            self._samples.append((now, v))
            self._prune(now, self.window_s)
            d = self._windowed(now, self.window_s)
            if d is None:
                return None, False
            rate = d[1] / d[0]
            return rate, _OPS[self.op](rate, self.bound)
        # burn_rate
        err, tot = stats.get(self.metric), stats.get(self.total)
        if not isinstance(err, (int, float)) \
                or not isinstance(tot, (int, float)):
            return None, False
        if self._samples and (err < self._samples[-1][1]
                              or tot < self._samples[-1][2]):
            self._samples = []            # counter reset — rebase
        self._samples.append((now, err, tot))
        self._prune(now, self.long_s)
        burns = []
        for w in (self.window_s, self.long_s):
            d = self._windowed(now, w)
            if d is None or d[2] <= 0:
                return None, False
            burns.append((d[1] / d[2]) / self.budget)
        return burns[0], all(b >= self.factor for b in burns)

    def _prune(self, now, keep_s):
        """Drop samples older than the window, keeping ONE as the
        window baseline."""
        cut = 0
        for i, s in enumerate(self._samples):
            if now - s[0] >= keep_s:
                cut = i
            else:
                break
        if cut:
            del self._samples[:cut]

    def _tick(self, stats, now):
        """Advance the state machine one evaluation tick. Returns
        "fire"/"resolve" on a transition, else None. Counter/flight
        writes happen HERE — only armed rules tick, so the disarmed
        path never creates an alerts/* stat."""
        value, breach = self._eval(stats, now)
        self.value = value
        ev = None
        if breach:
            self.clear_streak = 0
            if self.state != FIRING:
                self.streak += 1
                if self.streak >= self.for_ticks:
                    self.state = FIRING
                    self.fired += 1
                    ev = "fire"
                else:
                    self.state = PENDING
        else:
            self.streak = 0
            if self.state == PENDING:
                self.state = RESOLVED if self.fired else OK
            elif self.state == FIRING:
                self.clear_streak += 1
                if self.clear_streak >= self.clear_ticks:
                    self.state = RESOLVED
                    ev = "resolve"
        if ev is not None:
            _cmon.stat_set(f"alerts/{self.name}/firing",
                           1 if ev == "fire" else 0)
            _cmon.stat_add(f"alerts/{self.name}/transitions", 1)
            _flight.record(f"alert_{ev}", name=self.name,
                           rule_kind=self.kind, metric=self.metric,
                           value=value, bound=self.bound)
            try:
                _cmon.VLOG(0, f"alerts: {self.name} -> {self.state}"
                              f" (value={value}, bound={self.bound})")
            except Exception:
                pass
        return ev

    def describe(self):
        d = {"name": self.name, "kind": self.kind,
             "metric": self.metric, "state": self.state,
             "value": self.value, "streak": self.streak,
             "fired": self.fired, "for": self.for_ticks,
             "clear": self.clear_ticks}
        if self.op is not None:
            d["op"], d["bound"] = self.op, self.bound
        if self.kind == "quantile":
            d["q"], d["min_n"] = self.q, self.min_n
        if self.kind in ("rate", "burn_rate"):
            d["window_s"] = self.window_s
        if self.kind == "burn_rate":
            d.update(long_s=self.long_s, budget=self.budget,
                     factor=self.factor, total=self.total)
        if self.kind == "fraction":
            d["of"] = self.of
        return d


def default_rules():
    """The serving rule pack (`PADDLE_ALERTS=serving`) — the SLO
    signals PR 15/19 already measure, with production-shaped default
    bounds (override by spelling the rule out in the spec)."""
    return [
        AlertRule("serve/hist/ttft_us", "quantile", name="ttft_p99",
                  q=0.99, gt=500_000.0),
        AlertRule("serve/hist/itl_us", "quantile", name="itl_p99",
                  q=0.99, gt=100_000.0),
        AlertRule("serve/shed", "rate", name="shed_rate", gt=1.0,
                  window=60.0),
        AlertRule("serve/queue_depth", "threshold",
                  name="queue_depth", gt=64.0),
        AlertRule("serve/kv_blocks/free", "fraction",
                  name="kv_free_frac", of="serve/kv_blocks/used",
                  lt=0.1),
        # straggler persistence: a replica staying unhealthy across
        # 3 ticks (transient failover blips stay quiet)
        AlertRule("serve/replica/*/healthy", "threshold",
                  name="replica_unhealthy", lt=1.0, **{"for": 3}),
    ]


def parse_spec(spec):
    """`metric:kind[:param=value]*[;...]` -> [AlertRule]; the bare
    words serving/default/all/1/on/true expand to default_rules().
    Raises ValueError on anything unknown (the chaos-spec contract:
    loud, never silently misarmed)."""
    out = []
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        if part.lower() in _DEFAULT_WORDS:
            out.extend(default_rules())
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(
                f"alert rule {part!r} needs at least metric:kind")
        params = {}
        for field in fields[2:]:
            if "=" not in field:
                raise ValueError(
                    f"alert param {field!r} in {part!r} is not "
                    "key=value")
            k, v = field.split("=", 1)
            params[k.strip()] = v.strip()
        out.append(AlertRule(fields[0].strip(), fields[1].strip(),
                             **params))
    return out


# ---------------------------------------------------------------------------
# module state + evaluation
# ---------------------------------------------------------------------------

# _armed is THE zero-overhead gate (module attribute, chaos pattern)
_rules: list = []
_armed = False
_spec = ""
_listeners: list = []
_evaluator = None
_lock = _sanitize.lock("monitor.alerts")


def armed():
    return _armed


def rules():
    with _lock:
        return list(_rules)


def add_listener(fn):
    """Register fn(rule, transition, value) for every
    fire/resolve — the Autoscaler's subscription point. Best-effort:
    listener exceptions count under alerts/listener_errors and never
    reach the evaluator loop."""
    with _lock:
        if fn not in _listeners:
            _listeners.append(fn)
    return fn


def remove_listener(fn):
    with _lock:
        try:
            _listeners.remove(fn)
        except ValueError:
            pass


def _notify(rule, transition, value):
    with _lock:
        fns = list(_listeners)
    for fn in fns:
        try:
            fn(rule, transition, value)
        except Exception:
            _cmon.stat_add("alerts/listener_errors", 1)


def evaluate_once(now=None):
    """One evaluation tick over every armed rule; returns the
    [(rule, "fire"/"resolve", value)] transitions. The evaluator
    thread calls this on its cadence; tests call it directly for
    deterministic ticks. Forces a flight-ring stat sync FIRST
    (satellite 1): the ring amortizes flight/* gauge pushes to every
    256th event, and an alert must see the gauge a record() just
    moved, not the value from 255 events ago."""
    if not _armed:
        return []
    now = time.monotonic() if now is None else now
    _flight.sync_stats()
    stats = _cmon.registry.snapshot()
    with _lock:
        live = list(_rules)
    out = []
    for rule in live:
        try:
            ev = rule._tick(stats, now)
        except Exception:
            _cmon.stat_add("alerts/eval_errors", 1)
            continue
        if ev is not None:
            out.append((rule, ev, rule.value))
    _cmon.stat_add("alerts/ticks", 1)
    for rule, ev, value in out:
        _notify(rule, ev, value)
    return out


class AlertEvaluator:
    """The background cadence: one daemon thread waking every
    `interval_s` to evaluate_once(). Exists ONLY while rules are
    armed (configure starts it, disarm joins it) — the disarmed
    process has no alert thread to find."""

    def __init__(self, interval_s=None):
        self.interval_s = (env_interval_s() if interval_s is None
                           else max(0.05, float(interval_s)))
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle-alert-evaluator",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def running(self):
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                evaluate_once()
            except Exception:
                # a torn registry mid-shutdown must not kill the
                # evaluator for the rest of the run — count and keep
                # ticking
                _cmon.stat_add("alerts/eval_errors", 1)


def configure(spec=None, rules=None, start=True, interval_s=None):
    """Arm the rules a spec (default: $PADDLE_ALERTS) and/or explicit
    AlertRule list describe. Replaces any previous configuration;
    empty/unset disarms. `start=False` arms without the evaluator
    thread (tests drive evaluate_once() deterministically). Returns
    the armed rule list."""
    global _rules, _armed, _spec, _evaluator
    if spec is None and rules is None:
        spec = os.environ.get("PADDLE_ALERTS", "")
    parsed = list(rules or [])
    if spec:
        parsed = parse_spec(spec) + parsed
    names = [r.name for r in parsed]
    dup = {n for n in names if names.count(n) > 1}
    if dup:
        raise ValueError(
            f"duplicate alert rule name(s) {sorted(dup)} — set "
            "name=<unique> on one of them")
    disarm()
    if not parsed:
        return []
    with _lock:
        _rules = parsed
        _armed = True
        _spec = str(spec) if spec else ""
    _cmon.stat_set("alerts/armed", len(parsed))
    for r in parsed:
        # publish the armed-but-ok shape (firing=0, transitions=0)
        # so the fleet rollup can tell "armed, quiet" from "alerts
        # never armed on this rank"
        _cmon.stat_set(f"alerts/{r.name}/firing", 0)
        _cmon.registry.get(f"alerts/{r.name}/transitions")
    _flight.record("alert_arm", spec=_spec or None,
                   rules=len(parsed), names=names)
    try:
        _cmon.VLOG(0, f"alerts: armed {len(parsed)} rule(s): "
                      f"{', '.join(names)}")
    except Exception:
        pass
    if start:
        with _lock:
            _evaluator = AlertEvaluator(interval_s).start()
    return parsed


def disarm():
    """Stop the evaluator thread and drop every rule. Zeroes the
    alerts/armed gauge only if arming ever created it (the sanitize
    pattern — a disarmed run must leave ZERO alerts/* stats)."""
    global _rules, _armed, _spec, _evaluator
    with _lock:
        ev, _evaluator = _evaluator, None
        _rules = []
        _armed = False
        _spec = ""
    if ev is not None:
        ev.stop()
    if "alerts/armed" in _cmon.registry._stats:
        _cmon.stat_set("alerts/armed", 0)


def describe():
    """JSON-able engine state: spec, cadence, every rule with its
    live pending/firing/resolved state — the /alertz payload and the
    "alerts" section of every flight dump bundle."""
    with _lock:
        live = list(_rules)
        ev = _evaluator
    return {"armed": _armed, "spec": _spec or None,
            "interval_s": (ev.interval_s if ev is not None
                           else env_interval_s()),
            "evaluating": ev is not None and ev.running(),
            "rules": [r.describe() for r in live]}


# env-driven autostart (the chaos/exporter pattern): setting
# PADDLE_ALERTS is enough for any run importing paddle_tpu to arm the
# rules. A typo'd spec must be LOUD but must not break import.
if os.environ.get("PADDLE_ALERTS"):
    try:
        configure()
    except ValueError as _e:
        _cmon.stat_add("alerts/spec_errors", 1)
        try:
            _cmon.VLOG(0, f"alerts: IGNORING invalid PADDLE_ALERTS "
                          f"spec ({_e}) — validate with `python -m "
                          "paddle_tpu.monitor alerts`")
        except Exception:
            pass
