"""paddle_tpu.monitor.trace — per-request serving traces (ISSUE 15).

The serving engine's counters say how MUCH (tokens, evictions,
decode_us); they cannot say WHY one request's token arrived 400 ms
late. This module threads a `trace_id` through every stage a request
crosses — submit/route, admission, prefill, every decode token,
eviction + recompute-on-readmit, drain export, failover
import-and-replay, and the terminal state — so a slow token is
attributable to queue-wait vs eviction-recompute vs failover-replay
from the request's own timeline:

  * `mint()` — a globally-unique trace id
    (`<rank>:<pid hex>:<seq hex>`), minted at `LLMEngine.add_request`
    / `Router.submit` (the scheduler's `Request` ctor calls it) and
    PRESERVED across export/import: the replayed request on a
    survivor replica carries the dying replica's trace_id.
  * `note(req, stage, **data)` — appends one `{ts, stage, ...}` event
    to the request's bounded timeline (`Request.trace`,
    PADDLE_TRACE_EVENTS cap; drops counted per-request and under
    `trace/dropped`) and mirrors it into the flight ring (kind
    "trace") so dump bundles show the per-request story next to the
    engine spans. Armed by default; PADDLE_TRACE_SERVE=0 disarms —
    call sites gate on the module flag `trace._armed` (the chaos
    pattern), so the disarmed path is one attribute read and leaves
    ZERO counters behind (the PR-9/12 bench-provenance contract).
    Armed cost is one list append + one ring record — the PR-3
    ~3 us/event budget.
  * `export_requests()` / `to_chrome()` — a JSON trace spool (schema
    "paddle_tpu.trace/1") per engine/router, rendered to a
    chrome-trace by `python -m paddle_tpu.monitor trace` with the
    merge-traces pid layout (rank r -> pid r*stride + 1, one tid per
    request) so serving timelines land beside merged profiler traces
    in one Perfetto view.

Read a live request's timeline directly:
`engine.get_request(req_id).trace`.
"""
from __future__ import annotations

import itertools
import os
import time

from ..core import monitor as _cmon
from . import flight as _flight

__all__ = ["TRACE_SCHEMA", "mint", "note", "arm", "disarm",
           "max_events", "export_requests", "to_chrome"]

TRACE_SCHEMA = "paddle_tpu.trace/1"

# armed is THE hot-path gate (module attribute, read not called) —
# serving call sites guard with `if _trace._armed:` exactly like
# chaos._armed, so PADDLE_TRACE_SERVE=0 costs one attr read per site
_armed = _flight._env_on("PADDLE_TRACE_SERVE", True)

_seq = itertools.count(1)


def max_events():
    """PADDLE_TRACE_EVENTS — per-request timeline cap (default 256).
    Read per call so tests can retune; a request's decode loop is the
    only unbounded producer (one event per token)."""
    return max(8, _flight._env_int("PADDLE_TRACE_EVENTS", 256))


def arm(on=True):
    """Flip tracing on/off (tests; production uses
    PADDLE_TRACE_SERVE)."""
    global _armed
    _armed = bool(on)
    return _armed


def disarm():
    return arm(False)


def mint():
    """Globally-unique trace id: `<rank>:<pid hex>:<seq hex>` — the
    rank+pid legs keep ids distinct across replicas and relaunches,
    the seq leg within a process."""
    return (f"{_flight._rank()}:{os.getpid():x}:"
            f"{next(_seq):x}")


def note(req, stage, **data):
    """Append one stage event to `req.trace` (bounded) and mirror it
    into the flight ring. No-op (one flag read) when disarmed; a
    request minted while disarmed (trace_id None) stays untraced even
    if tracing arms later — half a timeline would misattribute every
    gap before the arm."""
    if not _armed or req.trace_id is None:
        return
    tl = req.trace
    if len(tl) >= max_events():
        req.trace_dropped += 1
        _cmon.stat_add("trace/dropped", 1)
        return
    ev = {"ts": round(time.time(), 6), "stage": stage}
    if data:
        ev.update(data)
    tl.append(ev)
    _cmon.stat_add("trace/events", 1)
    _flight.record("trace", trace_id=req.trace_id, req=req.req_id,
                   stage=stage, **data)


# ---------------------------------------------------------------------------
# Spool + chrome-trace rendering
# ---------------------------------------------------------------------------

def export_requests(requests, rank=None, extra=None):
    """JSON-ready trace spool over Request-like objects (anything
    with req_id/trace_id/state/output_ids/trace/trace_dropped).
    Untraced requests (disarmed at mint time) are skipped."""
    entries = []
    for r in requests:
        if getattr(r, "trace_id", None) is None:
            continue
        e = {"req_id": r.req_id, "trace_id": r.trace_id,
             "state": r.state, "tokens": len(r.output_ids),
             "events": list(r.trace), "dropped": r.trace_dropped}
        if extra:
            e.update(extra)
        entries.append(e)
    return {"schema": TRACE_SCHEMA,
            "rank": _flight._rank() if rank is None else int(rank),
            "ts": round(time.time(), 3),
            "requests": entries}


def to_chrome(spools, pid_stride=100000):
    """Chrome-trace events for one or more trace spools, laid out
    merge-traces-compatibly: rank r's events land on pid
    `r*pid_stride + 1` (pid 0 is the profiler's host-span track in a
    merged file), one tid per request with a thread_name metadata row
    naming `req_id [trace_id]`. Consecutive stage events become ph
    "X" spans (each stage's duration = gap to the next event — the
    queue-wait / recompute / replay attribution), the final event an
    instant; every event's data rides in args."""
    events = []
    tid_seq = itertools.count(1)
    for spool in spools:
        rank = int(spool.get("rank") or 0)
        pid = rank * int(pid_stride) + 1
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"rank{rank} serving"}})
        for entry in spool.get("requests") or []:
            evs = entry.get("events") or []
            if not evs:
                continue
            tid = next(tid_seq)
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tid,
                "args": {"name": f"{entry.get('req_id')} "
                                 f"[{entry.get('trace_id')}]"}})
            for i, ev in enumerate(evs):
                args = {k: v for k, v in ev.items()
                        if k not in ("ts", "stage")}
                args["trace_id"] = entry.get("trace_id")
                ts_us = float(ev["ts"]) * 1e6
                if i + 1 < len(evs):
                    dur = max(0.0,
                              (float(evs[i + 1]["ts"]) - float(ev["ts"]))
                              * 1e6)
                    events.append({"ph": "X", "name": ev["stage"],
                                   "ts": ts_us, "dur": dur,
                                   "pid": pid, "tid": tid,
                                   "args": args})
                else:
                    events.append({"ph": "i", "s": "t",
                                   "name": ev["stage"], "ts": ts_us,
                                   "pid": pid, "tid": tid,
                                   "args": args})
    return {"traceEvents": events,
            "metadata": {"source": TRACE_SCHEMA,
                         "pid_stride": int(pid_stride)}}
