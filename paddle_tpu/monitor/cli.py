"""`python -m paddle_tpu.monitor` — offline forensics tooling over the
artifacts the runtime leaves behind:

  inspect <bundle.json> [--json] [--stacks] [--events N]
      Pretty-print a flight dump bundle (watchdog / crash / sigusr1 —
      schema "paddle_tpu.flight/1"); --json re-emits the raw bundle.

  merge-traces -o merged.json rank0.json rank1.json ...
      Merge per-rank chrome traces (profiler.Profiler.export output)
      into ONE Perfetto-loadable file: rank r's pids shift by
      r * stride, with process_name metadata so tracks read
      "rank1 host" / "rank1 pid1000". The rank comes from a
      `rank<N>` token in the filename, else the argument position.

  tail <metrics.jsonl> [--keys p1,p2] [--all]
      Summarize a monitor.MetricsExporter JSON-lines trail: flush
      cadence per rank + the latest snapshot's interesting stats.

  memory [--json] [--top K]
      THIS process's memory report: device stats (PJRT or census),
      per-program HBM footprints off the live jit caches, and the
      live-array census grouped by shape/dtype. Mostly useful
      in-process (cli.main(["memory"]) from a REPL/debug hook) —
      a fresh CLI process has no arrays of its own.

  chaos [spec] [--json]
      List the fault-injection sites/faults/params and validate a
      PADDLE_CHAOS spec (the positional spec, else $PADDLE_CHAOS):
      prints the parsed rules, or an `error: ...` + exit 2 on an
      invalid spec — run it before launching a chaos job.

  trace spool.json ... [-o chrome.json] [--pid-stride N]
      Render per-request serving trace spools (engine/router
      `dump_traces()` output, schema "paddle_tpu.trace/1") to ONE
      chrome trace with the merge-traces pid layout (rank r -> pid
      r*stride + 1, one tid per request) — serving timelines land
      beside merged profiler traces in a single Perfetto view.
      Without -o, prints each request's stage-by-stage timeline
      (the queue-wait / recompute / replay attribution) as text.

  fleet rank0.jsonl rank1.json ... [--json] [--threshold X]
      Merge per-rank telemetry artifacts (exporter .jsonl trails,
      flight dump bundles, raw telemetry snapshots) into one fleet
      view — counters summed, gauges per-rank, histograms
      bucket-merged with fleet p50/p99 — and flag stragglers
      (per-rank mean step time vs the fleet median, flagged ranks
      attributed with their longest flight spans and — when the
      spool carries per-program dispatch histograms — their
      slowest program).

  serve [port] [--host H]
      Run THIS process's live introspection server (monitor.server)
      in the foreground until interrupted — the debug pages over an
      otherwise-idle process, mostly for smoke tests and scrape
      development. Real jobs arm via PADDLE_MONITOR_SERVE instead.

  scrape host:port ... [--json] [--threshold X] [--timeout S]
      The `fleet` report against RUNNING processes: pull each
      target's /metrics?format=json (+ /statusz, /flightz) and run
      the same merge + straggler detection the bundle-driven path
      uses. Unreachable targets degrade to a partial report with
      exit 1 (exit 2 when nothing answers).

  perf [bundle.json] [--json]
      Roofline attribution (ISSUE 16): the perf/program/* cost
      ledger joined with measured dispatch histograms into
      per-program achieved FLOP/s, arithmetic intensity and MFU
      against the device-kind peak table, with a compute/HBM/comm
      -bound verdict per program. Reads THIS process's live
      registries by default, or a flight dump bundle / telemetry
      snapshot JSON for offline forensics.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

# word boundary before "rank" so e.g. "crank2.json" doesn't parse as
# rank 2
_RANK_RE = re.compile(r"(?<![A-Za-z])rank[_-]?(\d+)", re.IGNORECASE)


# ---------------------------------------------------------------------------
# inspect
# ---------------------------------------------------------------------------

def _fmt_ts(ts):
    import datetime

    try:
        return datetime.datetime.fromtimestamp(float(ts)).isoformat(
            sep=" ", timespec="seconds")
    except (TypeError, ValueError, OSError, OverflowError):
        return str(ts)


def cmd_inspect(args):
    with open(args.bundle) as f:
        bundle = json.load(f)
    if args.json:
        json.dump(bundle, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
        return 0
    out = []
    reason = bundle.get("reason", "?")
    out.append(f"== flight dump: {reason} "
               f"(rank {bundle.get('rank')}, "
               f"pid {bundle.get('pid')}, "
               f"host {bundle.get('host')}) ==")
    out.append(f"schema {bundle.get('schema')}   "
               f"at {_fmt_ts(bundle.get('ts'))}   "
               f"world_size {bundle.get('world_size')}")
    exc = bundle.get("exception")
    if exc:
        out.append("")
        out.append(f"exception: {exc.get('type')}: "
                   f"{exc.get('message')}")
        for line in exc.get("traceback") or []:
            out.append("  " + line.rstrip("\n"))
    stuck = bundle.get("stuck")
    if stuck:
        out.append("")
        out.append(f"stuck ops (> {bundle.get('timeout_s')}s):")
        for e in stuck:
            out.append(f"  {e.get('kind')}/{e.get('name')}  "
                       f"age {e.get('age_s')}s  tid {e.get('tid')}"
                       + (f"  bytes {e['bytes']}"
                          if e.get("bytes") else ""))
    inflight = bundle.get("in_flight") or []
    if inflight and not stuck:
        out.append("")
        out.append("in flight at dump time:")
        for e in inflight:
            out.append(f"  {e.get('kind')}/{e.get('name')}  "
                       f"age {e.get('age_s')}s  tid {e.get('tid')}")
    threads = bundle.get("threads") or []
    out.append("")
    out.append(f"threads: {len(threads)}")
    for t in threads:
        stack = t.get("stack") or []
        if args.stacks:
            out.append(f"  -- {t.get('name')} (tid {t.get('tid')}):")
            for line in stack:
                out.append("  " + line.rstrip("\n"))
        else:
            top = stack[-1].strip().splitlines()[0] if stack else "?"
            out.append(f"  {t.get('name')} (tid {t.get('tid')}): "
                       f"{top}")
    if not args.stacks:
        out.append("  (--stacks for full stacks)")
    tail_evs = bundle.get("flight_tail") or []
    shown = tail_evs[-args.events:] if args.events > 0 else []
    out.append("")
    out.append(f"flight tail ({len(shown)} of {len(tail_evs)} "
               "recorded events):")
    for ev in shown:
        extra = {k: v for k, v in ev.items()
                 if k not in ("ts", "tid", "kind") and v is not None}
        out.append(f"  {_fmt_ts(ev.get('ts'))}  "
                   f"{str(ev.get('kind', '?')):<18s} "
                   + " ".join(f"{k}={v}" for k, v in extra.items()))
    tele = bundle.get("telemetry") or {}
    stats = tele.get("stats") if isinstance(tele, dict) else None
    if stats:
        out.append("")
        out.append(f"telemetry: {len(stats)} stats; highlights:")
        for k in sorted(stats):
            if k.startswith(("step/", "flight/", "monitor/export")):
                out.append(f"  {k} = {stats[k]}")
    caches = bundle.get("jit_caches")
    if isinstance(caches, list) and caches:
        out.append("")
        out.append("jit program caches:")
        for c in caches:
            line = (f"  {c.get('kind')}:{c.get('fn')}  "
                    f"entries={c.get('entries')}")
            m = c.get("memory")
            note = ""
            if isinstance(m, list):  # to_static: per-entry dicts
                dicts = [d for d in m if d]
                # show the LARGEST entry (the one an OOM cares
                # about), flagged when other entries exist
                m = max(dicts,
                        key=lambda d: d.get("total_bytes", 0),
                        default=None)
                if len(dicts) > 1:
                    note = f" (largest of {len(dicts)} entries)"
            if isinstance(m, dict):
                line += ("  mem arg={} temp={} out={}{}".format(
                    _fmt_bytes(m.get("argument_bytes")),
                    _fmt_bytes(m.get("temp_bytes")),
                    _fmt_bytes(m.get("output_bytes")), note))
            out.append(line)
    # memory section (absent in pre-PR5 paddle_tpu.flight/1 bundles —
    # tolerated: the schema only ADDED the key)
    mem = bundle.get("memory")
    if isinstance(mem, dict) and not mem.get("uninitialized"):
        out.append("")
        out.extend(_memory_lines(mem))
    print("\n".join(out))
    return 0


def _fmt_bytes(n):
    """Human bytes: the census/report tables print 1.5GiB, not
    1610612736."""
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return (f"{int(n)}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024.0


def _memory_lines(mem):
    """Render a memory report/section dict (bundle `memory` key or
    monitor.memory.memory_report()) as indented text lines."""
    out = []
    if mem.get("error"):
        return [f"memory: unavailable ({mem['error']})"]
    if mem.get("uninitialized"):
        return ["memory: no jax backend initialized yet (the report "
                "never initializes one itself)"]
    dev = mem.get("device") or {}
    out.append(f"memory ({dev.get('source', '?')}): "
               f"allocated {_fmt_bytes(dev.get('allocated_bytes'))}, "
               f"peak {_fmt_bytes(dev.get('peak_bytes'))}")
    progs = mem.get("programs") or {}
    if progs:
        out.append("  program footprints:")
        for name in sorted(
                progs, key=lambda n: -(progs[n] or {}).get(
                    "total_bytes", 0)):
            p = progs[name] or {}
            out.append(
                f"    {name}: total {_fmt_bytes(p.get('total_bytes'))}"
                f"  (arg {_fmt_bytes(p.get('argument_bytes'))}, "
                f"temp {_fmt_bytes(p.get('temp_bytes'))}, "
                f"out {_fmt_bytes(p.get('output_bytes'))}, "
                f"code {_fmt_bytes(p.get('generated_code_bytes'))})")
    census = mem.get("census")
    if isinstance(census, dict):
        shown = census.get("groups") or []
        out.append(
            f"  live arrays: {census.get('total_arrays')} arrays, "
            f"{_fmt_bytes(census.get('total_bytes'))} in "
            f"{census.get('group_count')} shape/dtype groups"
            + (f" (top {len(shown)} shown)"
               if census.get("truncated") else ""))
        for g in shown:
            shape = "x".join(str(d) for d in g.get("shape") or []) \
                or "scalar"
            out.append(f"    {_fmt_bytes(g.get('bytes')):>10s}  "
                       f"{g.get('count'):>5d} x {shape} "
                       f"{g.get('dtype')}")
    return out


# ---------------------------------------------------------------------------
# memory (live, this-process report)
# ---------------------------------------------------------------------------

def cmd_memory(args):
    from . import memory as mem_mod

    report = mem_mod.memory_report(args.top)
    if args.json:
        json.dump(report, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
        return 0
    print("\n".join(_memory_lines(report)))
    return 0


# ---------------------------------------------------------------------------
# perf (roofline attribution: live registries or a dump bundle)
# ---------------------------------------------------------------------------

def _fmt_flops(n):
    """Human FLOP count (the byte formatter's decimal sibling)."""
    if n is None:
        return "?"
    n = float(n)
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000.0 or unit == "P":
            return (f"{n:.0f}{unit}" if unit == ""
                    else f"{n:.2f}{unit}")
        n /= 1000.0


def _perf_lines(rep):
    """Render a perf_report() dict as indented text lines."""
    out = []
    pk = rep.get("peaks") or {}
    out.append(f"perf: device {pk.get('device_kind', '?')} "
               f"(peak table: {pk.get('matched', '?')}) — "
               f"{pk.get('peak_tflops')} TFLOP/s, "
               f"HBM {pk.get('hbm_gbps')} GB/s, "
               f"ICI {pk.get('ici_gbps')} GB/s")
    comm = rep.get("comm") or {}
    out.append(f"  comm leg: {_fmt_bytes(comm.get('wire_bytes'))} "
               f"on the wire (~{comm.get('est_us')}us at ICI "
               f"bandwidth, {100 * (comm.get('frac') or 0.0):.1f}% "
               f"of {rep.get('measured_total_us')}us measured "
               "dispatch time)")
    progs = rep.get("programs") or {}
    if not progs:
        out.append("  no perf/program/* ledger entries — "
                   "PADDLE_PERF_PROGRAM=0, or nothing compiled yet")
        return out
    out.append("  roofline ledger (by flops):")
    for name in sorted(progs,
                       key=lambda n: -(progs[n].get("flops") or 0)):
        e = progs[name]
        line = (f"    {name}: {_fmt_flops(e.get('flops'))}F, "
                f"{_fmt_bytes(e.get('bytes_accessed'))} accessed")
        if e.get("intensity") is not None:
            line += f", AI {e['intensity']}"
        d = e.get("dispatch")
        if d:
            line += (f", n={d['count']} p50={d['p50_us']}us "
                     f"p99={d['p99_us']}us")
        if e.get("achieved_gflops") is not None:
            line += f", {e['achieved_gflops']} GFLOP/s"
        if e.get("mfu") is not None:
            line += f", MFU {100 * e['mfu']:.2f}%"
        out.append(line + f"  -> {e.get('verdict')}")
    return out


def cmd_perf(args):
    from . import perf as perf_mod

    if args.bundle:
        with open(args.bundle) as f:
            bundle = json.load(f)
        # a flight dump bundle nests telemetry; a raw
        # telemetry_snapshot() / exporter record IS the telemetry
        tel = bundle.get("telemetry") or bundle
        stats = tel.get("stats")
        if not isinstance(stats, dict):
            raise ValueError(
                f"{args.bundle}: no telemetry stats found (expected "
                "a flight dump bundle or a telemetry snapshot)")
        report = perf_mod.perf_report(stats=stats,
                                      hists=tel.get("hists") or {})
    else:
        report = perf_mod.perf_report()
    if args.json:
        json.dump(report, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
        return 0
    print("\n".join(_perf_lines(report)))
    return 0


# ---------------------------------------------------------------------------
# chaos (site listing + spec validation)
# ---------------------------------------------------------------------------

def cmd_chaos(args):
    from . import chaos as chaos_mod

    spec = args.spec if args.spec is not None \
        else os.environ.get("PADDLE_CHAOS", "")
    parsed = None
    if spec:
        try:
            parsed = chaos_mod.parse_spec(spec)
        except ValueError as e:
            print(f"error: invalid chaos spec: {e}", file=sys.stderr)
            return 2
    if args.json:
        json.dump({"sites": chaos_mod.SITES,
                   "faults": chaos_mod.FAULTS,
                   "params": chaos_mod.PARAMS,
                   "spec": spec or None,
                   "rules": [r.describe() for r in parsed or []]},
                  sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0
    out = ["chaos injection sites (PADDLE_CHAOS = "
           "\"site:fault[:param=value]*[;...]\"):", ""]
    w = max(len(s) for s in chaos_mod.SITES)
    for s in sorted(chaos_mod.SITES):
        out.append(f"  {s:<{w}s}  {chaos_mod.SITES[s]}")
    out.append("")
    out.append("faults:")
    w = max(len(f) for f in chaos_mod.FAULTS)
    for f in sorted(chaos_mod.FAULTS):
        out.append(f"  {f:<{w}s}  {chaos_mod.FAULTS[f]}")
    out.append("")
    out.append("params:")
    w = max(len(p) for p in chaos_mod.PARAMS)
    for p in sorted(chaos_mod.PARAMS):
        out.append(f"  {p:<{w}s}  {chaos_mod.PARAMS[p]}")
    if parsed is not None:
        out.append("")
        out.append(f"spec OK — {len(parsed)} rule(s): {spec}")
        for r in parsed:
            d = r.describe()
            extra = " ".join(
                f"{k}={v}" for k, v in d.items()
                if k not in ("site", "fault", "calls", "triggers")
                and v is not None)
            out.append(f"  {d['site']}:{d['fault']}  {extra}")
    print("\n".join(out))
    return 0


# ---------------------------------------------------------------------------
# alerts (ISSUE 20)
# ---------------------------------------------------------------------------

def cmd_alerts(args):
    from . import alerts as alerts_mod

    spec = args.spec if args.spec is not None \
        else os.environ.get("PADDLE_ALERTS", "")
    parsed = None
    if spec:
        try:
            parsed = alerts_mod.parse_spec(spec)
        except ValueError as e:
            print(f"error: invalid alert spec: {e}", file=sys.stderr)
            return 2
    if args.json:
        json.dump({"kinds": alerts_mod.KINDS,
                   "params": alerts_mod.PARAMS,
                   "spec": spec or None,
                   "rules": [r.describe() for r in parsed or []],
                   "default_pack": [r.describe()
                                    for r in
                                    alerts_mod.default_rules()],
                   "live": alerts_mod.describe()},
                  sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0
    out = ["alert rule kinds (PADDLE_ALERTS = "
           "\"metric:kind[:param=value]*[;...]\"; bare `serving` "
           "arms the default pack):", ""]
    w = max(len(k) for k in alerts_mod.KINDS)
    for k in sorted(alerts_mod.KINDS):
        out.append(f"  {k:<{w}s}  {alerts_mod.KINDS[k]}")
    out.append("")
    out.append("params:")
    w = max(len(p) for p in alerts_mod.PARAMS)
    for p in sorted(alerts_mod.PARAMS):
        out.append(f"  {p:<{w}s}  {alerts_mod.PARAMS[p]}")
    out.append("")
    out.append("default serving pack (PADDLE_ALERTS=serving):")
    pack = alerts_mod.default_rules()
    w = max(len(r.name) for r in pack)
    for r in pack:
        d = r.describe()
        extra = " ".join(
            f"{k}={v}" for k, v in d.items()
            if k not in ("name", "kind", "metric", "state", "value",
                         "streak", "fired") and v is not None)
        out.append(f"  {r.name:<{w}s}  {r.kind}  {r.metric}  "
                   f"{extra}")
    if parsed is not None:
        out.append("")
        out.append(f"spec OK — {len(parsed)} rule(s): {spec}")
        for r in parsed:
            d = r.describe()
            extra = " ".join(
                f"{k}={v}" for k, v in d.items()
                if k not in ("name", "kind", "metric", "state",
                             "value", "streak", "fired")
                and v is not None)
            out.append(f"  {d['name']}  {d['kind']}  {d['metric']}  "
                       f"{extra}")
    print("\n".join(out))
    return 0


# ---------------------------------------------------------------------------
# merge-traces
# ---------------------------------------------------------------------------

def _rank_of(path, position):
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else position


def cmd_merge_traces(args):
    # resolve every rank up front: mixing filename-token and
    # positional assignment can collide (trace_rank1.json + an
    # unnamed file at position 1), which would silently interleave
    # two ranks' events under one pid space — refuse instead
    ranks = [_rank_of(path, pos)
             for pos, path in enumerate(args.traces)]
    dup = {r for r in ranks if ranks.count(r) > 1}
    if dup:
        print("merge-traces: inputs resolve to duplicate rank(s) "
              f"{sorted(dup)}: "
              + ", ".join(f"{p} -> rank{r}"
                          for p, r in zip(args.traces, ranks))
              + " — rename the files with distinct rankN tokens",
              file=sys.stderr)
        return 2
    loaded = []
    for path, rank in zip(args.traces, ranks):
        with open(path) as f:
            trace = json.load(f)
        evs = trace.get("traceEvents", trace) \
            if isinstance(trace, dict) else trace
        if not isinstance(evs, list):
            print(f"merge-traces: {path}: no traceEvents list",
                  file=sys.stderr)
            return 1
        loaded.append((rank, evs))
    # a pid >= stride would silently cross into the next rank's
    # shifted block (real OS pids can exceed the default 100000) —
    # widen the stride to keep rank pid spaces disjoint
    max_pid = max((ev["pid"] for _, evs in loaded for ev in evs
                   if isinstance(ev, dict)
                   and isinstance(ev.get("pid"), int)), default=0)
    stride = args.pid_stride
    if max_pid >= stride:
        stride = 10 ** len(str(max_pid))
        print(f"merge-traces: input pid {max_pid} >= stride "
              f"{args.pid_stride}; widening stride to {stride}",
              file=sys.stderr)
    merged = []
    for rank, evs in loaded:
        base = rank * stride
        seen_pids = set()
        named_pids = set()
        for ev in evs:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            pid = ev.get("pid")
            if isinstance(pid, int):
                ev["pid"] = base + pid
                seen_pids.add(pid)
                if ev.get("ph") == "M" \
                        and ev.get("name") == "process_name":
                    # input already labels this pid (e.g. the XPlane
                    # '/device:TPU:0' names) — prefix the rank, and
                    # DON'T synthesize a generic label below (viewers
                    # take the last process_name per pid)
                    named_pids.add(pid)
                    a = ev.get("args")
                    if isinstance(a, dict) and a.get("name"):
                        a["name"] = f"rank{rank} {a['name']}"
            elif pid is None:
                # pid-less events still need a disjoint-per-rank home
                ev["pid"] = base
                seen_pids.add(0)
            else:
                # string pids (named process groups): keep the name,
                # make it rank-unique
                ev["pid"] = f"rank{rank}/{pid}"
            ev.setdefault("args", {})
            if isinstance(ev["args"], dict):
                ev["args"].setdefault("rank", rank)
            merged.append(ev)
        # Perfetto labels: one named process group per (rank, pid)
        for pid in sorted(seen_pids - named_pids):
            label = f"rank{rank} host" if pid == 0 \
                else f"rank{rank} pid{pid}"
            merged.append({"ph": "M", "name": "process_name",
                           "pid": base + pid, "tid": 0,
                           "args": {"name": label}})
    out = {"traceEvents": merged,
           "metadata": {"merged_ranks": ranks,
                        "pid_stride": stride}}
    with open(args.output, "w") as f:
        json.dump(out, f)
    print(f"merged {len(args.traces)} trace(s), ranks {ranks}, "
          f"{len(merged)} events -> {args.output}")
    return 0


# ---------------------------------------------------------------------------
# tail
# ---------------------------------------------------------------------------

_DEFAULT_KEY_PREFIXES = ("step/", "flight/", "monitor/export",
                         "jit/train_step")


def cmd_tail(args):
    prefixes = tuple(p for p in (args.keys or "").split(",") if p) \
        or _DEFAULT_KEY_PREFIXES
    per_rank = {}
    bad = total = 0
    with open(args.jsonl) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            total += 1
            r = rec.get("rank", 0)
            ent = per_rank.setdefault(
                r, {"count": 0, "first_ts": rec.get("ts"),
                    "last": None})
            ent["count"] += 1
            ent["last"] = rec
    if not per_rank:
        print(f"{args.jsonl}: no valid exporter records"
              + (f" ({bad} unparsable lines)" if bad else ""))
        return 1
    print(f"{args.jsonl}: {total} flushes from "
          f"{len(per_rank)} rank(s)"
          + (f", {bad} unparsable line(s)" if bad else ""))
    for r in sorted(per_rank):
        ent = per_rank[r]
        last = ent["last"]
        span = (last.get("ts") or 0) - (ent["first_ts"] or 0)
        print(f"\nrank {r}: {ent['count']} flushes over "
              f"{span:.1f}s, last at {_fmt_ts(last.get('ts'))}")
        stats = last.get("stats") or {}
        keys = sorted(k for k in stats
                      if args.all or k.startswith(prefixes))
        for k in keys:
            print(f"  {k} = {stats[k]}")
        if not keys:
            print(f"  ({len(stats)} stats; none match "
                  f"{','.join(prefixes)} — use --all)")
    return 0


# ---------------------------------------------------------------------------
# trace (serving trace spools -> chrome trace / text timeline)
# ---------------------------------------------------------------------------

def cmd_trace(args):
    from . import trace as trace_mod

    spools = []
    for pos, path in enumerate(args.spools):
        with open(path) as f:
            spool = json.load(f)
        if not isinstance(spool, dict) or "requests" not in spool:
            print(f"trace: {path}: not a trace spool "
                  f"(expected schema {trace_mod.TRACE_SCHEMA})",
                  file=sys.stderr)
            return 1
        # filename rankN token overrides the recorded rank (replica
        # spools all record rank 0 in single-host tests; distinct
        # tokens keep their pid spaces disjoint in the merged view)
        m = _RANK_RE.search(os.path.basename(path))
        if m:
            spool = dict(spool, rank=int(m.group(1)))
        elif spool.get("rank") is None:
            spool = dict(spool, rank=pos)
        spools.append(spool)
    if args.output:
        doc = trace_mod.to_chrome(spools, pid_stride=args.pid_stride)
        with open(args.output, "w") as f:
            json.dump(doc, f)
        nreq = sum(len(s.get("requests") or []) for s in spools)
        print(f"rendered {len(spools)} spool(s), {nreq} request(s), "
              f"{len(doc['traceEvents'])} events -> {args.output}")
        return 0
    for spool in spools:
        reqs = spool.get("requests") or []
        print(f"rank {spool.get('rank')}: {len(reqs)} traced "
              f"request(s)")
        for entry in reqs:
            evs = entry.get("events") or []
            print(f"\n  {entry.get('req_id')} "
                  f"[{entry.get('trace_id')}]  "
                  f"state={entry.get('state')} "
                  f"tokens={entry.get('tokens')}"
                  + (f"  dropped={entry['dropped']}"
                     if entry.get("dropped") else ""))
            for i, ev in enumerate(evs):
                gap_ms = ((float(evs[i + 1]["ts"]) - float(ev["ts"]))
                          * 1e3 if i + 1 < len(evs) else None)
                extra = " ".join(
                    f"{k}={v}" for k, v in ev.items()
                    if k not in ("ts", "stage"))
                print(f"    {_fmt_ts(ev.get('ts'))}  "
                      f"{str(ev.get('stage', '?')):<12s}"
                      + (f" +{gap_ms:8.1f}ms" if gap_ms is not None
                         else " " * 11)
                      + (f"  {extra}" if extra else ""))
    return 0


# ---------------------------------------------------------------------------
# fleet (multi-rank telemetry merge + straggler report)
# ---------------------------------------------------------------------------

def _fleet_lines(view, show_all=False, noun="artifact"):
    """Text rendering of a fleet view — ONE renderer for both the
    bundle-driven `fleet` path and the live `scrape` path, so the
    straggler report reads identically however the records arrived."""
    from ..core.monitor import snapshot_quantile

    out = [f"fleet view over ranks {view['ranks']} "
           f"({len(view['sources'])} {noun}(s))"]
    counters = view.get("counters") or {}
    keys = sorted(k for k in counters
                  if show_all or k.startswith(
                      ("step/", "serve/", "comm/", "io/", "jit/")))
    if keys:
        out.append("")
        out.append(f"counters (summed over ranks; {len(counters)} "
                   "total):")
        for k in keys:
            out.append(f"  {k} = {counters[k]}")
    gauges = view.get("gauges") or {}
    gkeys = sorted(k for k in gauges
                   if show_all or k.startswith(
                       ("step/", "serve/", "mem/")))
    if gkeys:
        out.append("")
        out.append(f"gauges (per-rank — never summed; {len(gauges)} "
                   "total):")
        for k in gkeys:
            out.append("  " + k + "  " + "  ".join(
                f"r{r}={v}" for r, v in sorted(
                    gauges[k].items(), key=lambda kv: int(kv[0]))))
    hists = view.get("hists") or {}
    if hists:
        out.append("")
        out.append("histograms (bucket-merged):")
        for k in sorted(hists):
            s = hists[k]
            per_rank = s.get("rank_counts") or {}
            out.append(
                f"  {k}: n={s['count']}  "
                f"p50={snapshot_quantile(s, 0.5):.1f}  "
                f"p95={snapshot_quantile(s, 0.95):.1f}  "
                f"p99={snapshot_quantile(s, 0.99):.1f}  "
                "(per-rank n: "
                + ", ".join(f"r{r}={n}"
                            for r, n in sorted(per_rank.items()))
                + ")")
    strag = view.get("stragglers") or {}
    out.append("")
    step_ms = strag.get("step_ms") or {}
    if step_ms:
        out.append(f"step time per rank (median "
                   f"{strag.get('median_ms')}ms, straggler threshold "
                   f"{strag.get('threshold')}x):")
        for r in sorted(step_ms, key=int):
            out.append(f"  rank {r}: {step_ms[r]}ms")
        flagged = strag.get("stragglers") or []
        if flagged:
            for s in flagged:
                out.append(
                    f"  STRAGGLER rank {s['rank']}: "
                    f"{s['step_ms']}ms = {s['skew']}x median")
                for sp in s.get("top_spans") or []:
                    out.append(
                        f"    {sp['kind']}"
                        + (f"/{sp['name']}" if sp.get("name") else "")
                        + f"  {sp['dur_us']}us")
                prog = s.get("slowest_program")
                if prog:
                    out.append(
                        f"    slowest program: {prog['program']}  "
                        f"{prog['total_us']}us total over "
                        f"{prog['count']} dispatch(es), "
                        f"p50 {prog['p50_us']}us")
        else:
            out.append("  no stragglers flagged")
    else:
        out.append("no step/count in any artifact — straggler "
                   "detection needs step telemetry")
    al = view.get("alerts") or {}
    if al.get("armed_ranks"):
        out.append("")
        state = "FIRING" if al.get("any_firing") else "quiet"
        out.append(f"alerts ({state}; armed on ranks "
                   f"{al['armed_ranks']}):")
        for name in sorted(al.get("rules") or {}):
            slot = al["rules"][name]
            bits = [
                f"{st}=r{','.join(str(r) for r in slot[st])}"
                for st in ("firing", "resolved", "ok") if slot[st]]
            out.append(f"  {name}  " + "  ".join(bits))
    return out


def cmd_fleet(args):
    from . import fleet as fleet_mod

    view = fleet_mod.fleet_view(args.artifacts,
                                threshold=args.threshold)
    if args.json:
        json.dump(view, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
        return 0
    print("\n".join(_fleet_lines(view, show_all=args.all)))
    return 0


# ---------------------------------------------------------------------------
# serve / scrape (the live introspection plane, ISSUE 18)
# ---------------------------------------------------------------------------

def cmd_serve(args):
    from . import server as server_mod

    # a taken port propagates as OSError into main()'s exit-2 path
    srv = server_mod.serve(port=args.port, host=args.host)
    print(f"serving on {srv.url} — routes: "
          + " ".join(p for p, _, _ in server_mod.ROUTES))
    sys.stdout.flush()
    import time

    try:
        while srv.running():
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        server_mod.stop_server()
    return 0


def cmd_scrape(args):
    from . import fleet as fleet_mod

    records, failures = fleet_mod.scrape_records(
        args.targets, timeout=args.timeout,
        with_flight=not args.no_flight)
    for t in failures:
        print(f"scrape: {t}: {failures[t]}", file=sys.stderr)
    if not records:
        print("error: no scrape target reachable", file=sys.stderr)
        return 2
    view = fleet_mod.scrape_view(records, threshold=args.threshold)
    if args.json:
        json.dump(view, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        print("\n".join(_fleet_lines(view, show_all=args.all,
                                     noun="target")))
    # Router-heartbeat semantics: a half-dead fleet still reports,
    # but the exit code says it was partial
    return 1 if failures else 0


# ---------------------------------------------------------------------------

def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.monitor",
        description="Failure-forensics + observability CLI: inspect "
                    "flight dump bundles, merge per-rank chrome "
                    "traces, summarize exporter metrics trails, "
                    "report live memory, render per-request serving "
                    "traces, merge fleet telemetry with straggler "
                    "detection, and serve/scrape the live "
                    "introspection plane.")
    sub = p.add_subparsers(dest="cmd", required=True)

    pi = sub.add_parser(
        "inspect", help="pretty-print a flight dump bundle")
    pi.add_argument("bundle", help="path to a *_rank*_pid*.json dump")
    pi.add_argument("--json", action="store_true",
                    help="emit the raw bundle JSON")
    pi.add_argument("--stacks", action="store_true",
                    help="full per-thread stacks")
    pi.add_argument("--events", type=int, default=20,
                    help="flight-tail events to show (default 20)")
    pi.set_defaults(fn=cmd_inspect)

    pm = sub.add_parser(
        "merge-traces",
        help="merge per-rank chrome traces into one Perfetto file")
    pm.add_argument("traces", nargs="+",
                    help="per-rank trace JSONs (rank from a rankN "
                         "filename token, else argument order)")
    pm.add_argument("-o", "--output", required=True,
                    help="merged trace path")
    pm.add_argument("--pid-stride", type=int, default=100000,
                    help="pid offset per rank (default 100000)")
    pm.set_defaults(fn=cmd_merge_traces)

    pt = sub.add_parser(
        "tail", help="summarize a MetricsExporter .jsonl trail")
    pt.add_argument("jsonl", help="exporter output file")
    pt.add_argument("--keys",
                    help="comma-separated stat-name prefixes to show")
    pt.add_argument("--all", action="store_true",
                    help="show every stat in the latest snapshot")
    pt.set_defaults(fn=cmd_tail)

    pmem = sub.add_parser(
        "memory",
        help="live memory report for THIS process: device stats, "
             "program footprints, live-array census")
    pmem.add_argument("--json", action="store_true",
                      help="emit the raw report JSON")
    pmem.add_argument("--top", type=int, default=None,
                      help="census groups to show "
                           "(default PADDLE_MEM_CENSUS_TOP_K)")
    pmem.set_defaults(fn=cmd_memory)

    pch = sub.add_parser(
        "chaos",
        help="list fault-injection sites and validate a PADDLE_CHAOS "
             "spec")
    pch.add_argument("spec", nargs="?",
                     help="spec to validate (default: $PADDLE_CHAOS)")
    pch.add_argument("--json", action="store_true",
                     help="emit sites/faults/params + parsed rules as "
                          "JSON")
    pch.set_defaults(fn=cmd_chaos)

    pal = sub.add_parser(
        "alerts",
        help="list alert rule kinds/params + the default serving "
             "pack and validate a PADDLE_ALERTS spec")
    pal.add_argument("spec", nargs="?",
                     help="spec to validate (default: "
                          "$PADDLE_ALERTS)")
    pal.add_argument("--json", action="store_true",
                     help="emit kinds/params/default pack + parsed "
                          "rules + live engine state as JSON")
    pal.set_defaults(fn=cmd_alerts)

    ptr = sub.add_parser(
        "trace",
        help="render serving trace spools to a chrome trace / text "
             "timeline")
    ptr.add_argument("spools", nargs="+",
                     help="trace spool JSONs (engine/router "
                          "dump_traces output; rank from a rankN "
                          "filename token, else the recorded rank)")
    ptr.add_argument("-o", "--output",
                     help="write a chrome trace here (default: print "
                          "text timelines)")
    ptr.add_argument("--pid-stride", type=int, default=100000,
                     help="pid offset per rank, merge-traces "
                          "compatible (default 100000)")
    ptr.set_defaults(fn=cmd_trace)

    pf = sub.add_parser(
        "fleet",
        help="merge per-rank telemetry artifacts + straggler report")
    pf.add_argument("artifacts", nargs="+",
                    help="exporter .jsonl trails, flight dump "
                         "bundles, or telemetry snapshot JSONs "
                         "(one or more ranks each)")
    pf.add_argument("--json", action="store_true",
                    help="emit the merged fleet view as JSON")
    pf.add_argument("--threshold", type=float, default=None,
                    help="straggler skew threshold vs the fleet "
                         "median (default "
                         "PADDLE_MONITOR_STRAGGLER_X=1.25)")
    pf.add_argument("--all", action="store_true",
                    help="show every merged counter, not just the "
                         "step/serve/comm/io/jit families")
    pf.set_defaults(fn=cmd_fleet)

    ps = sub.add_parser(
        "serve",
        help="run THIS process's live introspection server in the "
             "foreground (mostly for smoke tests; real jobs arm via "
             "PADDLE_MONITOR_SERVE)")
    ps.add_argument("port", nargs="?", type=int, default=0,
                    help="port to bind (default 0 = ephemeral)")
    ps.add_argument("--host", default=None,
                    help="bind address (default "
                         "PADDLE_MONITOR_SERVE_HOST or 0.0.0.0)")
    ps.set_defaults(fn=cmd_serve)

    psc = sub.add_parser(
        "scrape",
        help="pull /metrics+/statusz from running debug servers and "
             "run the fleet merge + straggler report live")
    psc.add_argument("targets", nargs="+",
                     help="host:port of each rank's debug server")
    psc.add_argument("--json", action="store_true",
                     help="emit the merged fleet view as JSON")
    psc.add_argument("--threshold", type=float, default=None,
                     help="straggler skew threshold vs the fleet "
                          "median (default "
                          "PADDLE_MONITOR_STRAGGLER_X=1.25)")
    psc.add_argument("--all", action="store_true",
                     help="show every merged counter, not just the "
                          "step/serve/comm/io/jit families")
    psc.add_argument("--timeout", type=float, default=5.0,
                     help="per-request timeout in seconds "
                          "(default 5)")
    psc.add_argument("--no-flight", action="store_true",
                     help="skip the /flightz pull (straggler span "
                          "attribution) — faster, deterministic")
    psc.set_defaults(fn=cmd_scrape)

    pp = sub.add_parser(
        "perf",
        help="roofline attribution: per-program cost ledger + "
             "measured dispatch time vs the device peak table")
    pp.add_argument("bundle", nargs="?",
                    help="flight dump bundle or telemetry snapshot "
                         "JSON (default: THIS process's live "
                         "registries)")
    pp.add_argument("--json", action="store_true",
                    help="emit the raw report JSON")
    pp.set_defaults(fn=cmd_perf)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # output piped into `head`/`less` that exited — not an error;
        # point stdout at devnull so the interpreter's exit-time flush
        # doesn't print a second traceback
        os.dup2(os.open(os.devnull, os.O_WRONLY),
                sys.stdout.fileno())
        return 0
    except (OSError, ValueError) as e:
        # missing/unreadable/non-JSON input: the clean `error: ...` /
        # exit-2 contract the analysis CLI established — an operator
        # mid-incident gets a message, not a traceback
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
