"""paddle_tpu.monitor.server — live introspection plane (ISSUE 18).

Every telemetry surface so far is push-based (exporter textfiles,
JSON-lines trails, post-mortem dump bundles). A serving fleet needs
*pull*: a Prometheus-scrapeable endpoint, live debug pages, and a
controller-consumable signal feed. This module is that surface — a
stdlib-`http.server` debug server running on ONE daemon thread pool,
reading the same registries every existing artifact already reads:

    srv = monitor.serve(port=0)        # ephemeral port; srv.port
    curl http://host:8899/metrics      # Prometheus exposition
    curl http://host:8899/statusz      # build/device/env summary

Arming — `PADDLE_MONITOR_SERVE=<port>` arms `maybe_auto_serve()`,
which `hapi.Model.fit` and the serving `Router` call; with the env
var unset NOTHING happens (no thread, no socket, and lowering is
bit-identical — the zero-overhead contract every monitor leg keeps).
`maybe_auto_serve` additionally refuses to arm from inside a jax
trace: a server whose lifetime depends on how many times a function
was TRACED (rather than called) would be a trace-time side effect,
exactly the class PTA040 lints against.

Endpoints (ROUTES below is the single source of truth; the README
endpoints table is doc-drift-gated against it):

    /metrics    Prometheus exposition — the SAME renderer the
                MetricsExporter prom textfile path uses
                (monitor.prometheus_text); ?format=json returns the
                raw telemetry_snapshot() (what `monitor scrape`
                pulls: byte-identical stats/hists to a dump bundle's
                telemetry section)
    /healthz    liveness: 200 "ok"
    /statusz    build/device/env/server summary (JSON)
    /flightz    flight-ring tail (?n=256; ?format=chrome for a
                Perfetto-loadable span view)
    /memz       memory ledger report (device stats + per-program
                footprints + live-array census)
    /perfz      roofline ledger report (perf.perf_report())
    /tracez     recent per-request serving trace spools from every
                live engine (registered weakly — a GC'd engine
                drops out)
    /profilez   on-demand capture window: ?duration_ms=N records a
                flight-ring segment (+ a jax.profiler chrome trace
                unless ?profiler=0) and returns the bundle

Evidence-gathering discipline: the handler thread must never
INITIALIZE a jax backend (the same rule flight's dump path keeps) —
/memz, /perfz and /statusz device sections degrade to
`{"uninitialized": true}` until the main thread brings backends up.

Shutdown is IDEMPOTENT and total — stop_server() / shutdown() can be
called twice, from atexit, or from the flight excepthook's crash
path without raising, so a crashing run still emits its dump bundle
(which names the armed server under its "server" key).
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..core import monitor as _cmon
from . import flight as _flight
from . import sanitize as _sanitize

__all__ = [
    "ROUTES", "DebugServer", "serve", "get_server", "stop_server",
    "maybe_auto_serve", "add_trace_source", "trace_spools",
    "describe", "flight_chrome",
]

# (path, payload, armed-by) — the single source of truth the README
# endpoints table is doc-drift-gated against (tests diff the table
# rows against this tuple, the PR-17 PTA-code gate pattern).
ROUTES = (
    ("/metrics", "Prometheus exposition of the full StatRegistry "
                 "(scalars + _bucket histograms); ?format=json for "
                 "the raw telemetry_snapshot()", "always"),
    ("/healthz", "liveness: 200 `ok`", "always"),
    ("/statusz", "version/rank/device/env/server summary (JSON)",
     "always"),
    ("/flightz", "flight-ring tail as JSON (?n=256) or chrome trace "
                 "(?format=chrome)", "PADDLE_FLIGHT_ENABLE"),
    ("/memz", "memory report: device stats, per-program HBM "
              "footprints, live-array census", "PADDLE_MEM_PROGRAM"),
    ("/perfz", "roofline ledger: per-program FLOPs/bytes, measured "
               "dispatch quantiles, MFU, verdicts",
     "PADDLE_PERF_PROGRAM"),
    ("/tracez", "per-request serving trace spools from every live "
                "engine", "PADDLE_TRACE_SERVE"),
    ("/profilez", "on-demand capture window (?duration_ms=N, "
                  "?profiler=0 for flight-only); returns the bundle",
     "always"),
    ("/alertz", "alert engine state: spec, cadence, every rule with "
                "its live pending/firing/resolved state + last value",
     "PADDLE_ALERTS"),
)

PROFILEZ_SCHEMA = "paddle_tpu.profilez/1"

# duration clamp for /profilez — an unbounded duration would park a
# handler thread (and the profiler lock) for hours on one typo'd curl
_PROFILEZ_MAX_MS = 60_000


def _env_port():
    """PADDLE_MONITOR_SERVE — unset/empty/off/false/no means DISARMED
    (no thread, no socket); otherwise the port to bind. Unlike the
    boolean PADDLE_* knobs, `0` here is NOT falsy: it arms an
    EPHEMERAL port (the OS picks; /statusz and the monitor_serve
    flight event record which) — the only way a test fleet on one
    host avoids port races. Returns None when disarmed, the int port
    when armed."""
    v = os.environ.get("PADDLE_MONITOR_SERVE", "").strip()
    if not v or v.lower() in ("false", "off", "no"):
        return None
    try:
        return int(v)
    except ValueError:
        return None


def _env_host():
    """PADDLE_MONITOR_SERVE_HOST — bind address (default 0.0.0.0 so
    fleet-wide `monitor scrape` reaches the rank; set 127.0.0.1 to
    keep the debug pages loopback-only)."""
    return os.environ.get("PADDLE_MONITOR_SERVE_HOST") or "0.0.0.0"


def _in_trace():
    """True inside a jax trace — serve() must never arm there (a
    traced fit would start one server per TRACE, a classic trace-time
    side effect). Total fallback: an unimportable/old jax reads as
    not-tracing."""
    try:
        import jax

        return not jax.core.trace_state_clean()
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Trace sources (/tracez)
# ---------------------------------------------------------------------------

# weak refs to live engines' export_traces bound methods — an engine
# registers at construction and simply falls out when collected; no
# unregister ceremony on the serving hot path
_trace_sources: list = []
_trace_lock = _sanitize.lock("monitor.server.traces")


def add_trace_source(method):
    """Register a bound `export_traces`-style method (weakly) whose
    spool /tracez should include. Idempotent per live object."""
    ref = weakref.WeakMethod(method)
    with _trace_lock:
        live = []
        for r in _trace_sources:
            m = r()
            if m is None:
                continue
            if m.__self__ is method.__self__:
                return  # already registered
            live.append(r)
        live.append(ref)
        _trace_sources[:] = live


def trace_spools():
    """Spools from every still-live registered source; a source that
    raises contributes an error entry instead of killing the page."""
    with _trace_lock:
        refs = list(_trace_sources)
    out = []
    for r in refs:
        m = r()
        if m is None:
            continue
        try:
            out.append(m())
        except Exception as e:
            out.append({"error": f"{type(e).__name__}: {e}",
                        "source": repr(m.__self__)})
    return out


# ---------------------------------------------------------------------------
# Payload builders (shared by the handler and tests)
# ---------------------------------------------------------------------------

def _statusz(server=None):
    from .. import version as _version

    return {
        "ok": True,
        "version": _version.full_version,
        "schema_flight": _flight.DUMP_SCHEMA,
        "ts": round(time.time(), 3),
        "rank": _flight._rank(),
        "world_size": _flight._world_size(),
        "pid": os.getpid(),
        "host": __import__("socket").gethostname(),
        "uptime_s": (None if server is None
                     else round(time.monotonic() - server._t0, 3)),
        # evidence-gathering rule: never initialize a backend from
        # the handler thread — degrade to {"uninitialized": true}
        "device": _flight._device_info(),
        "env": _flight._env_info(),
        "server": describe(server),
    }


def flight_chrome(events, pid=None):
    """Chrome-trace doc over flight-ring events: `*_end` events (they
    carry dur_us) become ph "X" spans ending at their record time,
    everything else an instant — the quick Perfetto look at a live
    rank without a full profiler capture."""
    pid = os.getpid() if pid is None else pid
    out = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"rank{_flight._rank()} flight"}}]
    for ev in events:
        ts_us = float(ev.get("ts", 0.0)) * 1e6
        kind = str(ev.get("kind", "?"))
        args = {k: v for k, v in ev.items()
                if k not in ("ts", "tid", "kind") and v is not None}
        dur = ev.get("dur_us")
        if kind.endswith("_end") and dur:
            out.append({"ph": "X", "name": kind[:-4],
                        "ts": ts_us - float(dur), "dur": float(dur),
                        "pid": pid, "tid": ev.get("tid", 0),
                        "args": args})
        else:
            out.append({"ph": "i", "s": "t", "name": kind,
                        "ts": ts_us, "pid": pid,
                        "tid": ev.get("tid", 0), "args": args})
    return {"traceEvents": out,
            "metadata": {"source": "paddle_tpu.flightz"}}


def _memz():
    if not _flight._jax_backends_live():
        return {"uninitialized": True}
    from . import memory as _memory

    return _memory.memory_report()


def _perfz():
    from . import perf as _perf

    if not _flight._jax_backends_live():
        # device_peaks would otherwise be the page's only backend
        # toucher; it self-guards too (belt and braces), this keeps
        # the whole payload honest about why it is empty
        return {"uninitialized": True,
                "programs": {}, "peaks": None}
    return _perf.perf_report()


_profilez_lock = _sanitize.lock("monitor.server.profilez")


def _profilez(duration_ms, use_profiler=True):
    """One on-demand capture window: sleep `duration_ms` recording
    the flight ring (and, unless disabled or impossible, a
    jax.profiler trace via paddle_tpu.profiler.Profiler), return the
    JSON bundle. Serialized — concurrent windows would fight over
    the single jax profiler session."""
    duration_ms = max(1, min(int(duration_ms), _PROFILEZ_MAX_MS))
    if not _profilez_lock.acquire(blocking=False):
        return None  # caller turns this into a 409
    try:
        t0 = time.time()
        bundle = {"schema": PROFILEZ_SCHEMA,
                  "rank": _flight._rank(),
                  "pid": os.getpid(),
                  "ts": round(t0, 3),
                  "duration_ms": duration_ms,
                  "chrome_trace": None, "profiler_error": None}
        prof = None
        if use_profiler and _flight._jax_backends_live():
            try:
                from .. import profiler as _profiler

                prof = _profiler.Profiler()
                prof.start()
            except Exception as e:
                prof = None
                bundle["profiler_error"] = \
                    f"{type(e).__name__}: {e}"
        _flight.record("profilez_begin", duration_ms=duration_ms)
        time.sleep(duration_ms / 1e3)  # noqa: PTA062 — single-flight lock: every other path is acquire(blocking=False) → 409, no waiter ever blocks here
        if prof is not None:
            try:
                import tempfile

                prof.stop()
                with tempfile.TemporaryDirectory(
                        prefix="paddle_profilez_") as d:
                    path = os.path.join(d, "trace.json")
                    prof.export(path)
                    with open(path) as f:  # noqa: PTA062 — tmpdir read under the same no-waiter single-flight lock
                        bundle["chrome_trace"] = json.load(f)
            except Exception as e:
                bundle["profiler_error"] = \
                    f"{type(e).__name__}: {e}"
        # the window's flight segment: everything stamped since t0
        # (epsilon for same-tick events), profilez_begin included
        bundle["flight"] = [
            ev for ev in _flight.recorder.tail()
            if ev.get("ts", 0.0) >= t0 - 1e-6]
        _flight.record("profilez_end",
                       events=len(bundle["flight"]))
        from . import telemetry_snapshot

        bundle["telemetry"] = telemetry_snapshot()
        _cmon.stat_add("monitor/serve/profilez", 1)
        return bundle
    finally:
        _profilez_lock.release()


# ---------------------------------------------------------------------------
# The HTTP server
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    # the handler is stateless; the DebugServer rides on the server
    # object (self.server.debug)
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # a scrape per rank per 15s would
        pass                    # otherwise flood every rank's stderr

    def _send(self, code, body, ctype="application/json"):
        if isinstance(body, str):
            body = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-reply — not our problem

    def _send_json(self, doc, code=200):
        self._send(code, json.dumps(doc, default=str) + "\n")

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        try:
            self._route()
        except Exception as e:
            # one bad page must not kill the scrape target
            _cmon.stat_add("monitor/serve/errors", 1)
            try:
                self._send_json(
                    {"error": f"{type(e).__name__}: {e}"}, code=500)
            except Exception:
                pass

    def _route(self):
        u = urlparse(self.path)
        q = {k: v[-1] for k, v in parse_qs(u.query).items()}
        path = u.path.rstrip("/") or "/"
        _cmon.stat_add("monitor/serve/requests", 1)
        if path == "/healthz":
            self._send(200, "ok\n", ctype="text/plain; charset=utf-8")
        elif path == "/metrics":
            from . import prometheus_text, telemetry_snapshot

            fn = getattr(self.server.debug, "snapshot_fn", None)
            snap = fn() if fn is not None else telemetry_snapshot()
            if q.get("format") == "json":
                self._send_json(snap)
            else:
                self._send(
                    200, prometheus_text(snap),
                    ctype="text/plain; version=0.0.4; charset=utf-8")
        elif path == "/statusz":
            self._send_json(_statusz(self.server.debug))
        elif path == "/flightz":
            try:
                n = int(q.get("n", 256))
            except ValueError:
                n = 256
            events = _flight.recorder.tail(n)
            if q.get("format") == "chrome":
                self._send_json(flight_chrome(events))
            else:
                self._send_json({"rank": _flight._rank(),
                                 "events": events,
                                 "ring": _flight.recorder.stats()})
        elif path == "/memz":
            self._send_json(_memz())
        elif path == "/perfz":
            self._send_json(_perfz())
        elif path == "/tracez":
            self._send_json({"rank": _flight._rank(),
                             "spools": trace_spools()})
        elif path == "/profilez":
            try:
                dur = int(q.get("duration_ms", 500))
            except ValueError:
                dur = 500
            bundle = _profilez(
                dur, use_profiler=q.get("profiler", "1")
                not in ("0", "false", "off", "no"))
            if bundle is None:
                self._send_json(
                    {"error": "another capture window is running"},
                    code=409)
            else:
                self._send_json(bundle)
        elif path == "/alertz":
            # lazy import (alerts imports this module's sibling
            # surfaces); force the flight-ring stat sync first
            # (ISSUE 20 satellite 1) so a scrape sees the same
            # registry truth the evaluator does
            from . import alerts as _alerts
            _flight.sync_stats()
            doc = dict(_alerts.describe())
            doc["rank"] = _flight._rank()
            self._send_json(doc)
        elif path == "/":
            index = {p: desc for p, desc, _ in ROUTES}
            self._send_json({"paddle_tpu": True, "routes": index})
        else:
            self._send_json({"error": f"no such page {path!r}",
                             "routes": [p for p, _, _ in ROUTES]},
                            code=404)


class DebugServer:
    """One ThreadingHTTPServer on a named daemon thread. start() binds
    (raising OSError on a taken port — callers decide whether that is
    fatal), shutdown() is idempotent and never raises."""

    def __init__(self, port=0, host=None, snapshot_fn=None):
        self._requested_port = int(port)
        self.host = _env_host() if host is None else str(host)
        # /metrics source override — the process-wide server reads the
        # global telemetry_snapshot(); embedders (and the scrape
        # byte-compat tests, which run N "ranks" in one process) can
        # serve a per-instance snapshot instead
        self.snapshot_fn = snapshot_fn
        self._httpd = None
        self._thread = None
        self._t0 = time.monotonic()
        self._lock = _sanitize.lock("monitor.server.lifecycle")

    @property
    def port(self):
        h = self._httpd
        return h.server_address[1] if h is not None \
            else self._requested_port

    @property
    def url(self):
        host = self.host if self.host not in ("", "0.0.0.0") \
            else "127.0.0.1"
        return f"http://{host}:{self.port}"

    def running(self):
        t = self._thread
        return t is not None and t.is_alive()

    def start(self):
        with self._lock:
            if self.running():
                return self
            httpd = ThreadingHTTPServer(
                (self.host, self._requested_port), _Handler)
            httpd.daemon_threads = True
            httpd.debug = self
            self._httpd = httpd
            self._t0 = time.monotonic()
            t = threading.Thread(
                target=httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="paddle-monitor-serve", daemon=True)
            self._thread = t
            t.start()
        _cmon.stat_set("monitor/serve/port", self.port)
        _flight.record("monitor_serve", port=self.port,
                       host=self.host)
        return self

    def shutdown(self, timeout=5.0):
        """Idempotent, exception-free teardown — safe from atexit,
        tests, and the crash path (a dying run's excepthook must
        still get its dump bundle out; a raising shutdown here would
        mask the original exception)."""
        try:
            with self._lock:
                httpd, self._httpd = self._httpd, None
                thread, self._thread = self._thread, None
            if httpd is not None:
                httpd.shutdown()
                httpd.server_close()
            if thread is not None and thread.is_alive():
                thread.join(timeout=timeout)
        except Exception:
            _cmon.stat_add("monitor/serve/errors", 1)


# ---------------------------------------------------------------------------
# Process-wide lifecycle
# ---------------------------------------------------------------------------

_server = None
_server_lock = _sanitize.lock("monitor.server")
_arm_error_logged = False
_atexit_registered = False


def get_server():
    return _server


def describe(server=None):
    """JSON-ready summary of the (given or process-wide) server —
    embedded in /statusz and in flight dump bundles, so post-mortems
    name the port that was armed."""
    s = _server if server is None else server
    if s is None:
        return {"running": False}
    return {"running": s.running(), "port": s.port, "host": s.host,
            "routes": [p for p, _, _ in ROUTES]}


def serve(port=0, host=None):
    """Start (or return) the process-wide debug server. Raises
    OSError when the requested port cannot bind — explicit callers
    should hear about a taken port; the env-armed path
    (maybe_auto_serve) downgrades that to a counter + one VLOG."""
    global _server, _atexit_registered
    with _server_lock:
        if _server is not None and _server.running():
            return _server
        srv = DebugServer(port=port, host=host).start()
        _server = srv
        if not _atexit_registered:
            # clean socket close on interpreter exit; stop_server is
            # idempotent and exception-free, so this is safe beside
            # the flight excepthook's crash-dump path
            _atexit_registered = True
            import atexit

            atexit.register(stop_server)
        return srv


def stop_server():
    """Idempotent process-wide teardown."""
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.shutdown()
    _cmon.stat_set("monitor/serve/port", 0)


def maybe_auto_serve(where=""):
    """Env-gated serve() called from hapi.Model.fit and the serving
    Router: arms only when PADDLE_MONITOR_SERVE names a port, never
    from inside a jax trace, never twice, and never raises — a taken
    port on a debug surface must not kill the training run it is
    observing (counted under monitor/serve/errors, VLOGged once)."""
    global _arm_error_logged
    port = _env_port()
    if port is None:
        return None
    if _server is not None and _server.running():
        return _server
    if _in_trace():
        # trace-time import/side-effect hazard (the PTA040 class):
        # arming under trace would tie server lifetime to retrace
        # count — refuse; the next EAGER call site arms it
        _cmon.stat_add("monitor/serve/trace_skips", 1)
        return None
    try:
        srv = serve(port=port)
    except OSError as e:
        _cmon.stat_add("monitor/serve/errors", 1)
        if not _arm_error_logged:
            _arm_error_logged = True
            try:
                _cmon.VLOG(0, f"monitor.server: could not bind port "
                              f"{port} at {where or '?'} ({e}); "
                              "live introspection disabled")
            except Exception:
                pass
        return None
    _flight.record("auto_serve", where=where, port=srv.port)
    return srv
