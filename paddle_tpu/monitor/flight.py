"""paddle_tpu.monitor.flight — black-box flight recorder + hang/crash
forensics.

The reference stack diagnoses wedged or dead distributed runs from
artifacts (VLOG trails, per-op timelines, the distributed hang dumps
around collective ops); this module is that capability for the TPU
stack, distinct from the opt-in profiler: it is ALWAYS on, cheap
enough to leave armed in production, and it answers "what were the
last things this rank did" after the fact.

Four pieces:

  * FlightRecorder — a process-wide bounded ring of structured events
    (step begin/end, jit cache hit/miss, compile begin/end, collective
    begin/end with op/group/bytes, io fetch, exception, dump), fed by
    the same layers the monitor counters instrument. Appending is one
    lock + deque append (registry gauges amortize to every 256th
    event); the ring drops the oldest event when full and counts
    drops under flight/ring/dropped.

  * in-flight registry + Watchdog — begin()/end() (or the in_flight()
    context manager) mark a thread inside a potentially-blocking
    operation (collective, compile). The watchdog thread scans the
    registry and, once an entry exceeds PADDLE_WATCHDOG_TIMEOUT_S,
    writes a per-rank dump (all-thread stacks, the flight-ring tail,
    telemetry snapshot) instead of letting the slice hang silently —
    asymmetric collective participation is the dominant multi-slice
    failure mode (EQuARX; PAPERS.md).

  * dump bundles — write_dump() produces one JSON file per incident
    (schema "paddle_tpu.flight/1"): reason, rank/pid/host, env,
    device info, in-flight ops, per-thread stacks, flight tail,
    telemetry snapshot, jit program-cache keys. install_excepthook()
    writes one on any unhandled exception; dump_on_crash() is the
    context-manager flavor for worker threads; install_signal_handler
    wires SIGUSR1 for live dumps of a healthy-looking run.

  * arming — arm() switches everything on; maybe_auto_arm() is called
    from hapi.Model.fit and distributed.init_parallel_env and arms by
    default for distributed runs (PADDLE_TRAINERS_NUM > 1), gated by
    PADDLE_FLIGHT_AUTOARM=0/1.

Counters (exporter + bench.py pick these up with every snapshot):
flight/events, flight/ring/dropped, flight/watchdog/fires,
flight/dumps_written, flight/watchdog/errors.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import signal
import socket
import sys
import threading
import time
import traceback
from collections import deque

from ..core import monitor as _cmon
from . import sanitize as _sanitize

__all__ = [
    "DUMP_SCHEMA", "FlightRecorder", "recorder", "record", "tail",
    "sync_stats", "begin", "end", "in_flight", "inflight_snapshot",
    "Watchdog",
    "start_watchdog", "stop_watchdog", "get_watchdog", "write_dump",
    "dump_dir", "install_excepthook", "uninstall_excepthook",
    "dump_on_crash", "install_signal_handler",
    "uninstall_signal_handler", "arm", "maybe_auto_arm",
    "add_incident_hook", "remove_incident_hook",
]

DUMP_SCHEMA = "paddle_tpu.flight/1"


# ONE home for the env-knob parsers (the PR-13 dedup discipline),
# shared with core.monitor's Histogram config — aliased here because
# every monitor-side consumer (chaos, trace, fleet, serving) reaches
# them as flight._env_*
_env_int = _cmon._env_int
_env_float = _cmon._env_float


_FALSY = ("0", "false", "off", "no")


def _env_on(name, default=True):
    v = os.environ.get(name, "").strip().lower()
    if not v:
        return default
    return v not in _FALSY


def _jax_backends_live():
    """distributed.env._jax_ready with a total fallback — evidence
    gathering must not MUTATE backend state (see env.py), and must
    survive a half-broken package."""
    try:
        from ..distributed.env import _jax_ready

        return _jax_ready()
    except Exception:
        return False


def _rank():
    """distributed.env.peek_rank — the side-effect-free rank (never
    initializes a jax backend; never raises) — with a total fallback
    for crash paths where the distributed package itself may be
    broken. Lazy import: the distributed package must not load just
    because flight did."""
    try:
        from ..distributed.env import peek_rank

        return int(peek_rank())
    except Exception:
        try:
            return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        except ValueError:
            return 0


def _world_size():
    """distributed.env.peek_world_size (side-effect-free; by fit/init
    time backends are live, so jax-native multi-host launches still
    auto-arm), with the same total fallback as _rank."""
    try:
        from ..distributed.env import peek_world_size

        return int(peek_world_size())
    except Exception:
        try:
            return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        except ValueError:
            return 1


def dump_dir():
    """Where watchdog/crash/signal bundles land: PADDLE_FLIGHT_DIR, or
    <tmp>/paddle_tpu_flight. Read per dump (not cached) so tests and
    late launcher setup can redirect it."""
    d = os.environ.get("PADDLE_FLIGHT_DIR")
    if not d:
        import tempfile

        d = os.path.join(tempfile.gettempdir(), "paddle_tpu_flight")
    return d


# ---------------------------------------------------------------------------
# Flight recorder ring
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of (ts, tid, kind, data) events.

    record() is the always-on hot path: one lock acquisition, one
    deque append, one stat bump — cheap enough to ride every jit cache
    hit and collective. PADDLE_FLIGHT_ENABLE=0 turns the whole layer
    (ring, in-flight registry, watchdog evidence) off;
    PADDLE_FLIGHT_CAPACITY sizes the ring (default 4096 events)."""

    def __init__(self, capacity=None, enabled=None):
        if capacity is None:
            capacity = _env_int("PADDLE_FLIGHT_CAPACITY", 4096)
        if enabled is None:
            enabled = _env_on("PADDLE_FLIGHT_ENABLE", True)
        self._ring = deque(maxlen=max(16, int(capacity)))
        # sanitize-aware (PADDLE_SANITIZE=locks): a plain Lock when
        # disarmed — record() is the always-on hot path
        self._lock = _sanitize.lock("flight.ring")
        self._seq = 0
        self._dropped = 0
        self.enabled = bool(enabled)

    @property
    def capacity(self):
        return self._ring.maxlen

    def record(self, kind, **data):
        if not self.enabled:
            return
        ev = (time.time(), threading.get_ident(), kind, data or None)
        with self._lock:
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(ev)
            sync = self._seq % 256 == 0
        # registry gauges amortize to every 256th event: a per-event
        # stat_add would DOUBLE the hot-path cost once the ring fills
        # (every append then drops); telemetry_snapshot() syncs too,
        # so exporter flushes and dump bundles are always fresh
        if sync:
            self.sync_stats()

    def sync_stats(self):
        """Push the ring's internal counters into the StatRegistry
        (flight/events, flight/ring/dropped)."""
        with self._lock:
            seq, dropped = self._seq, self._dropped
        _cmon.stat_set("flight/events", seq)
        _cmon.stat_set("flight/ring/dropped", dropped)

    def tail(self, n=None):
        """The newest `n` events (all when n is None, none when
        n <= 0), oldest first, as JSON-ready dicts."""
        with self._lock:
            evs = list(self._ring)
        if n is not None:
            # a plain [-n:] would invert n=0 into "everything"
            evs = evs[-int(n):] if int(n) > 0 else []
        return [dict({"ts": round(ts, 6), "tid": tid, "kind": kind},
                     **(data or {}))
                for ts, tid, kind, data in evs]

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = 0

    def stats(self):
        with self._lock:
            return {"events": self._seq, "dropped": self._dropped,
                    "capacity": self._ring.maxlen,
                    "size": len(self._ring)}


recorder = FlightRecorder()


def record(kind, **data):
    """Append one structured event to the process-wide flight ring."""
    recorder.record(kind, **data)


def tail(n=None):
    return recorder.tail(n)


def sync_stats():
    recorder.sync_stats()


# ---------------------------------------------------------------------------
# In-flight registry (what the watchdog watches)
# ---------------------------------------------------------------------------

_inflight: dict = {}
_inflight_lock = _sanitize.lock("flight.inflight")
_token_seq = itertools.count(1)


def begin(kind, name, **data):
    """Mark this thread entering a potentially-blocking operation.
    Records a `<kind>_begin` flight event and registers the op so the
    watchdog can see it wedge. Returns a token for end(); None when
    the recorder is disabled (end(None) is a no-op)."""
    if not recorder.enabled:
        return None
    recorder.record(f"{kind}_begin", name=name, **data)
    token = next(_token_seq)
    # t0 is wall clock for display; ages/durations measure against
    # the MONOTONIC clock — an NTP step or VM suspend must not fire
    # false watchdog dumps or yield negative dur_us
    entry = dict({"kind": kind, "name": name,
                  "tid": threading.get_ident(),
                  "t0": round(time.time(), 6),
                  "_t0m": time.monotonic()}, **data)
    with _inflight_lock:
        _inflight[token] = entry
    return token


def end(token):
    """Complete the operation begin() registered: drops it from the
    in-flight table and records the `<kind>_end` event with its
    duration."""
    if token is None:
        return
    with _inflight_lock:
        entry = _inflight.pop(token, None)
    if entry is not None:
        recorder.record(
            f"{entry['kind']}_end", name=entry["name"],
            dur_us=int((time.monotonic() - entry["_t0m"]) * 1e6))


@contextlib.contextmanager
def in_flight(kind, name, **data):
    token = begin(kind, name, **data)
    try:
        yield
    finally:
        end(token)


def inflight_snapshot(now=None):
    """Current in-flight ops with their ages — what a hung rank was
    doing, straight from the registry the hooks maintain. `now` is a
    time.monotonic() reading (the age clock)."""
    now = time.monotonic() if now is None else now
    with _inflight_lock:
        entries = [dict(e) for e in _inflight.values()]
    for e in entries:
        e["age_s"] = round(now - e.pop("_t0m"), 3)
    return entries


# ---------------------------------------------------------------------------
# Dump bundles
# ---------------------------------------------------------------------------

_dump_seq = itertools.count(1)


def _thread_stacks():
    """Formatted stacks of EVERY live thread (the py-spy-style view a
    hang dump needs: the stalled collective's thread plus whoever it
    is waiting on)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append({"tid": tid, "name": names.get(tid, "?"),
                    "stack": traceback.format_stack(frame)})
    return out


def _env_info():
    pfx = ("PADDLE_", "FLAGS_", "JAX_", "XLA_", "GLOG_", "TPU_")
    return {k: os.environ[k] for k in sorted(os.environ)
            if k.startswith(pfx)}


def _device_info():
    if not _jax_backends_live():
        # evidence-gathering must not MUTATE backend state: a dump
        # fired mid-rendezvous (watchdog thread) would otherwise
        # initialize a single-process backend under the main thread's
        # jax.distributed.initialize
        return {"uninitialized": True}
    try:
        import jax

        return {"backend": jax.default_backend(),
                "process_index": jax.process_index(),
                "process_count": jax.process_count(),
                "local_device_count": jax.local_device_count(),
                "device_count": jax.device_count()}
    except Exception as e:  # backend may be unusable mid-crash
        return {"error": f"{type(e).__name__}: {e}"}


def _jit_cache_info():
    try:
        from .. import jit as _jit

        return _jit.cache_report()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _memory_section(reason, full=None, jit_report=None):
    """Memory evidence for a dump bundle: device stats + per-program
    footprints in EVERY bundle (cheap reads); the live-array census
    joins for OOM and operator-requested (sigusr1) dumps, where "what
    is holding HBM" is the question being asked — `full` overrides
    the reason-based default either way. `jit_report` reuses the
    cache_report() the bundle already computed for its jit_caches
    key instead of walking the live compilers a second time.
    Evidence gathering must not initialize a backend mid-rendezvous
    (see _device_info)."""
    if not _jax_backends_live():
        return {"uninitialized": True}
    try:
        from . import memory as _memory

        if full is None:
            full = reason in ("oom", "sigusr1")
        return _memory.memory_section(census=full,
                                      jit_report=jit_report)
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _sanitize_section():
    try:
        return _sanitize.describe()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _numerics_section():
    try:
        from . import numerics

        return numerics.describe()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _server_section():
    """monitor.server.describe() with a total fallback — the dump
    path (which also runs from the excepthook) must survive a
    half-imported or torn-down server module."""
    try:
        from . import server as _server_mod

        return _server_mod.describe()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _alerts_section():
    """monitor.alerts.describe() with a total fallback — a dump taken
    before the alerts module finished importing (env autostart runs
    at import time) must still write."""
    try:
        from . import alerts as _alerts_mod

        return _alerts_mod.describe()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def write_dump(reason, extra=None, path=None, full_memory=None):
    """Write one self-contained JSON forensics bundle and return its
    path. Schema (DUMP_SCHEMA = "paddle_tpu.flight/1"):

        schema/reason/ts/rank/world_size/pid/host/argv — identity
        env          — PADDLE_/FLAGS_/JAX_/XLA_/GLOG_/TPU_ vars
        device       — jax backend + process/device counts
        in_flight    — ops currently inside begin()/end() with ages
        threads      — formatted stacks of every live thread
        flight_tail  — newest PADDLE_FLIGHT_DUMP_EVENTS ring events
        telemetry    — monitor.telemetry_snapshot() (full registry)
        jit_caches   — per-function compiled-program cache keys
        memory       — device stats + per-program footprints (+ the
          live-array census for oom/sigusr1 reasons; `full_memory`
          forces it on/off for custom reasons — oom_observer passes
          True so a renamed OOM bundle keeps its census)
        + reason-specific keys from `extra` (e.g. "exception",
          "stuck")

    The file lands in dump_dir() as
    <reason>_rank<r>_pid<p>_<n>.json (atomic tmp+rename), counted
    under flight/dumps_written, echoed at VLOG(0)."""
    ts = time.time()
    caches = _jit_cache_info()
    payload = {
        "schema": DUMP_SCHEMA,
        "reason": reason,
        "ts": round(ts, 3),
        "rank": _rank(),
        "world_size": _world_size(),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "argv": list(sys.argv),
        "env": _env_info(),
        "device": _device_info(),
        "in_flight": inflight_snapshot(),
        "threads": _thread_stacks(),
        "flight_tail": recorder.tail(
            _env_int("PADDLE_FLIGHT_DUMP_EVENTS", 256)),
        "jit_caches": caches,
        "memory": _memory_section(
            reason, full=full_memory,
            jit_report=caches if isinstance(caches, list) else None),
        # sanitizer state (ISSUE 10): which families were armed and
        # what they tracked/found — sanitize_arm/sanitize_finding
        # events ride the flight_tail, this is the summary
        "sanitize": _sanitize_section(),
        # numerics-probe state (ISSUE 17): freshest per-tensor
        # absmax/absmin/nonfinite stats — an overflow in this bundle
        # names the offending tensor, not just the skipped step
        "numerics": _numerics_section(),
        # live introspection plane (ISSUE 18): whether a debug server
        # was armed and on which port — a post-mortem can tell
        # whether /profilez etc. were scrapeable before the crash
        "server": _server_section(),
        # SLO alert engine (ISSUE 20): which rules were armed and
        # their pending/firing/resolved states at dump time — a
        # post-mortem can tell whether the SLOs were already burning
        # before the crash
        "alerts": _alerts_section(),
    }
    try:
        from . import telemetry_snapshot

        payload["telemetry"] = telemetry_snapshot()
    except Exception as e:
        payload["telemetry"] = {"error": f"{type(e).__name__}: {e}"}
    if extra:
        payload.update(extra)
    if path is None:
        d = dump_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"{reason}_rank{_rank()}_pid{os.getpid()}_"
               f"{next(_dump_seq)}.json")
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    os.replace(tmp, path)
    _cmon.stat_add("flight/dumps_written", 1)
    recorder.record("dump", reason=reason, path=path)
    try:
        _cmon.VLOG(0, f"flight: wrote {reason} dump -> {path}")
    except Exception:
        # broken/closed stderr must not make a dump that IS on disk
        # look failed (the watchdog would re-dump it every poll)
        pass
    return path


# ---------------------------------------------------------------------------
# Incident hooks (watchdog checkpoint-then-abort)
# ---------------------------------------------------------------------------

# callables fired with (reason) after an incident dump lands — the
# elastic CheckpointManager registers its emergency_save here so a
# hung collective leaves a RESUMABLE snapshot next to the bundle, not
# just an autopsy (ROADMAP item 4 "checkpoint-then-abort")
_incident_hooks: list = []


def add_incident_hook(fn):
    """Register fn(reason) to run after a watchdog incident dump.
    Hooks must be best-effort: exceptions are counted under
    flight/incident_hook/errors and never reach the watchdog loop."""
    if fn not in _incident_hooks:
        _incident_hooks.append(fn)
    return fn


def remove_incident_hook(fn):
    try:
        _incident_hooks.remove(fn)
    except ValueError:
        pass


def _run_incident_hooks(reason):
    for fn in list(_incident_hooks):
        try:
            fn(reason)
        except Exception:
            _cmon.stat_add("flight/incident_hook/errors", 1)


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Background thread that turns a silent hang into evidence.

    Scans the in-flight registry every `poll_s`; any op older than
    `timeout_s` (PADDLE_WATCHDOG_TIMEOUT_S, default 300 — generous
    enough to sit out a first XLA compile) triggers ONE dump naming
    every newly-stuck op. Each stuck op is reported once — a wedged
    collective doesn't re-dump at every poll, but a SECOND op wedging
    later still gets its own bundle."""

    def __init__(self, timeout_s=None, poll_s=None):
        if timeout_s is None:
            timeout_s = _env_float("PADDLE_WATCHDOG_TIMEOUT_S", 300.0)
        self.timeout_s = float(timeout_s)
        if poll_s is None:
            poll_s = _env_float("PADDLE_WATCHDOG_POLL_S", 0.0) \
                or max(0.05, min(self.timeout_s / 4.0, 10.0))
        self.poll_s = float(poll_s)
        self.fired = 0
        self._reported: set = set()   # dumped successfully
        self._noted: set = set()      # ring event recorded
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle-flight-watchdog",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Returns False when the thread did not exit within 5s (e.g.
        wedged inside write_dump on a hung filesystem) — it is NOT
        forgotten then (running() stays truthful); once it unblocks,
        the set stop event makes it exit without another scan."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            if t.is_alive():
                _cmon.stat_add("flight/watchdog/errors", 1)
                try:
                    _cmon.VLOG(0, "flight: watchdog thread did not "
                                  "stop within 5s (blocked dump?)")
                except Exception:
                    pass
                return False
        self._thread = None
        return True

    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def _loop(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:
                # the watchdog must NEVER take the training process
                # down — count and keep polling
                _cmon.stat_add("flight/watchdog/errors", 1)

    def check(self, now=None):
        """One scan; returns the dump path when it fired (tests call
        this directly). `now` is a time.monotonic() reading — ages
        ride the monotonic clock so wall-clock steps can't fake (or
        mask) a hang."""
        now = time.monotonic() if now is None else now
        with _inflight_lock:
            items = list(_inflight.items())
        live = {tok for tok, _ in items}
        self._reported &= live  # forget ops that completed
        self._noted &= live
        stuck = [(tok, e) for tok, e in items
                 if now - e["_t0m"] > self.timeout_s
                 and tok not in self._reported]
        if not stuck:
            return None
        detail = [dict(e, age_s=round(now - e["_t0m"], 3))
                  for _, e in stuck]
        for e in detail:
            e.pop("_t0m", None)
        # ring event once per stuck op (recorded BEFORE the dump so
        # its tail shows it) — NOT once per retry: a persistently
        # failing dump would otherwise flood the ring with watchdog
        # entries and evict the pre-hang evidence
        fresh = {tok for tok, _ in stuck} - self._noted
        if fresh:
            recorder.record("watchdog",
                            stuck=[e["name"] for _, e in stuck],
                            timeout_s=self.timeout_s)
            self._noted |= fresh
        path = write_dump(
            "watchdog",
            extra={"stuck": detail, "timeout_s": self.timeout_s})
        # mark reported only once the dump is ON DISK — a failed write
        # (unwritable dir, full disk) raises past here into _loop's
        # error counter and the next poll retries, instead of the
        # evidence being suppressed forever
        self._reported |= {tok for tok, _ in stuck}
        self.fired += 1
        _cmon.stat_add("flight/watchdog/fires", 1)
        # checkpoint-then-abort: incident hooks run AFTER the dump is
        # durable (the bundle is cheap and certain; a checkpoint may
        # take seconds and can itself wedge — its ckpt_write span
        # would then show in the NEXT dump)
        _run_incident_hooks("watchdog")
        if _env_on("PADDLE_WATCHDOG_ABORT", default=False):
            # elastic relaunch contract: with evidence + checkpoint on
            # disk, kill the wedged rank so the supervisor restarts
            # the job instead of burning the reservation on a hang
            recorder.record("watchdog_abort")
            try:
                _cmon.VLOG(0, "flight: watchdog aborting process "
                              "(PADDLE_WATCHDOG_ABORT=1)")
            except Exception:
                pass
            os.kill(os.getpid(), signal.SIGABRT)
        return path


_watchdog = None
_watchdog_lock = _sanitize.lock("flight.watchdog")


def get_watchdog():
    return _watchdog


def start_watchdog(timeout_s=None, poll_s=None):
    """Start (or return) the process-wide watchdog. Explicit args
    restart it with the new settings."""
    global _watchdog
    with _watchdog_lock:
        if _watchdog is not None:
            if timeout_s is None and poll_s is None \
                    and _watchdog.running():
                return _watchdog
            _watchdog.stop()
        _watchdog = Watchdog(timeout_s, poll_s).start()
        return _watchdog


def stop_watchdog():
    global _watchdog
    with _watchdog_lock:
        wd, _watchdog = _watchdog, None
    if wd is not None:
        wd.stop()


# ---------------------------------------------------------------------------
# Crash bundles (excepthook / context manager / SIGUSR1)
# ---------------------------------------------------------------------------

def _format_exception(etype, value, tb):
    return {"type": getattr(etype, "__name__", str(etype)),
            "message": str(value),
            "traceback": traceback.format_exception(etype, value, tb)}


def _crash_dump(etype, value, tb):
    # memory.oom_observer may already have bundled THIS exception
    # (with the census taken while the offending arrays were still
    # live) — the excepthook must not shadow it with a second dump
    if getattr(value, "_paddle_flight_dumped", False):
        return None
    recorder.record("exception",
                    type=getattr(etype, "__name__", str(etype)),
                    message=str(value)[:300])
    reason = "crash"
    try:
        from . import memory as _memory

        if _memory.is_oom_error(value):
            # RESOURCE_EXHAUSTED gets its own reason (an operator
            # greps for oom_rank*.json) and the full census in its
            # memory section (_memory_section keys off the reason)
            reason = "oom"
    except Exception:
        pass
    return write_dump(
        reason, extra={"exception": _format_exception(etype, value,
                                                      tb)})


_orig_excepthook = None
_orig_threading_hook = None
_excepthook_installed = False
_excepthook_running = False


def _flight_excepthook(etype, value, tb):
    global _excepthook_running
    if _excepthook_running:
        # re-entered through a hook cycle (a third-party hook chained
        # back to us) — break it, print the real traceback once
        sys.__excepthook__(etype, value, tb)
        return
    _excepthook_running = True
    try:
        if _excepthook_installed:
            try:
                _crash_dump(etype, value, tb)
            except Exception:
                pass  # forensics must not mask the original crash
        (_orig_excepthook or sys.__excepthook__)(etype, value, tb)
    finally:
        _excepthook_running = False


def _flight_threading_excepthook(args):
    """threading.excepthook leg: an unhandled exception on a WORKER
    thread (dataloader producer, user prefetch thread) never reaches
    sys.excepthook — without this, a run that degrades after a thread
    death leaves no bundle."""
    if _excepthook_installed \
            and args.exc_type is not SystemExit:
        try:
            _crash_dump(args.exc_type, args.exc_value,
                        args.exc_traceback)
        except Exception:
            pass
    (_orig_threading_hook or threading.__excepthook__)(args)


def install_excepthook():
    """Chain a crash-bundle writer in front of sys.excepthook AND
    threading.excepthook: any unhandled exception — main or worker
    thread — leaves one inspectable JSON bundle before the normal
    traceback prints. Idempotent — and guarded by a flag, NOT by
    `sys.excepthook is ours`: re-installing after a third-party hook
    wrapped us would capture that wrapper as our `orig` and
    crash-time dispatch would cycle forever."""
    global _orig_excepthook, _orig_threading_hook, \
        _excepthook_installed
    if _excepthook_installed:
        return
    if _orig_excepthook is None:
        _orig_excepthook = sys.excepthook
        sys.excepthook = _flight_excepthook
    if _orig_threading_hook is None:
        _orig_threading_hook = threading.excepthook
        threading.excepthook = _flight_threading_excepthook
    # else: a prior uninstall-while-wrapped left our link inside a
    # third-party chain with the app's original retained — re-enable
    # via the flag alone; re-capturing the hook here would capture
    # the wrapper (dispatch cycle) and drop the original
    _excepthook_installed = True


def uninstall_excepthook():
    global _orig_excepthook, _orig_threading_hook, \
        _excepthook_installed
    if not _excepthook_installed:
        return
    if sys.excepthook is _flight_excepthook:
        sys.excepthook = _orig_excepthook or sys.__excepthook__
        _orig_excepthook = None
    if threading.excepthook is _flight_threading_excepthook:
        threading.excepthook = _orig_threading_hook \
            or threading.__excepthook__
        _orig_threading_hook = None
    # else: someone wrapped us — leave the chain intact (our link
    # becomes a pass-through via the flag) and keep the originals so
    # the chains still terminate correctly
    _excepthook_installed = False


@contextlib.contextmanager
def dump_on_crash():
    """Context-manager flavor of the excepthook for code the top-level
    hook never sees (worker threads, callers that catch and exit):
    writes the crash bundle, then re-raises."""
    try:
        yield
    except Exception:
        try:
            _crash_dump(*sys.exc_info())
        except Exception:
            pass
        raise


_orig_sig_handler = None
_orig_sig_signum = None
_sig_installed = None
_sig_running = False


def _signal_handler(signum, frame):
    # NEVER dump inline: the handler runs between bytecodes on the
    # main thread, possibly while the interrupted frame holds
    # recorder._lock / _inflight_lock / a StatRegistry lock — none
    # reentrant, so write_dump() here could wedge the very rank the
    # live dump is inspecting. A spawned thread queues behind the
    # lock instead.
    global _sig_running
    if _sig_running:
        return  # handler-chain cycle — break it
    _sig_running = True
    try:
        if _sig_installed == signum:  # armed for THIS signal
            def _dump():
                try:
                    write_dump("sigusr1")
                except Exception:
                    pass

            threading.Thread(target=_dump,
                             name="paddle-flight-sigusr1",
                             daemon=True).start()
        # chain like the excepthook does: auto-arm must not eat an
        # application's own SIGUSR1 handler (e.g. the cluster
        # checkpoint-on-preemption trigger); the retained original
        # belongs to one specific signal
        if signum == _orig_sig_signum and callable(_orig_sig_handler):
            _orig_sig_handler(signum, frame)
    finally:
        _sig_running = False


def install_signal_handler(signum=None):
    """Wire SIGUSR1 (or `signum`) to a live dump — `kill -USR1 <pid>`
    inspects a running rank without stopping it. A previously
    installed application handler is chained (called after the dump
    thread is spawned), and uninstall_signal_handler restores it.
    Idempotent via an installed flag (NOT handler identity — see
    install_excepthook). ONE live-dump signal at a time: asking for a
    second signal while another is armed (or while a dormant chain on
    another signal still routes through us) returns False rather than
    claiming success. Also returns False where installing is
    impossible (no SIGUSR1 on the platform, or not the main
    thread)."""
    global _orig_sig_handler, _orig_sig_signum, _sig_installed
    if signum is None:
        signum = getattr(signal, "SIGUSR1", None)
        if signum is None:
            return False
    if _sig_installed is not None:
        return signum == _sig_installed
    if _orig_sig_handler is not None:
        if signum != _orig_sig_signum:
            # a dormant (uninstalled-while-wrapped) chain on another
            # signal still routes through us; rewiring for a second
            # signal would orphan that chain's original handler
            return False
        # prior uninstall-while-wrapped ON THIS SIGNAL: our link still
        # sits inside a third-party chain — re-enable via the flag
        # alone (see install_excepthook)
        _sig_installed = signum
        return True
    try:
        prev = signal.signal(signum, _signal_handler)
    except (ValueError, OSError):
        return False
    if prev is not _signal_handler:
        _orig_sig_handler = prev
        _orig_sig_signum = signum
    _sig_installed = signum
    return True


def uninstall_signal_handler():
    global _orig_sig_handler, _orig_sig_signum, _sig_installed
    if _sig_installed is None:
        return
    try:
        if signal.getsignal(_sig_installed) is _signal_handler:
            signal.signal(_sig_installed,
                          _orig_sig_handler or signal.SIG_DFL)
            _orig_sig_handler = None
            _orig_sig_signum = None
        # else: wrapped by a later handler — leave the chain intact
        # (the cleared _sig_installed makes our link dump-free) and
        # keep _orig_sig_handler so the chain still terminates
    except (ValueError, OSError):
        pass
    _sig_installed = None


# ---------------------------------------------------------------------------
# Arming
# ---------------------------------------------------------------------------

def arm(watchdog=True, excepthook=True, usr1=True, timeout_s=None,
        poll_s=None):
    """Switch the full forensics layer on (recorder is always on
    unless PADDLE_FLIGHT_ENABLE=0). Returns the watchdog (or None).
    With the recorder disabled the watchdog is skipped too — begin()
    registers nothing, so the thread would poll an always-empty table
    forever; crash/SIGUSR1 dumps still work (stacks + telemetry, just
    no ring tail)."""
    if excepthook:
        install_excepthook()
    if usr1:
        install_signal_handler()
    if watchdog and recorder.enabled:
        return start_watchdog(timeout_s, poll_s)
    return None


def maybe_auto_arm(where=""):
    """Env-gated arm() called from hapi.Model.fit and
    distributed.init_parallel_env: PADDLE_FLIGHT_AUTOARM set non-falsy
    forces on, falsy forces off; unset arms only distributed runs
    (PADDLE_TRAINERS_NUM > 1) — single-host notebooks keep their
    excepthook untouched unless they opt in."""
    if not _env_on("PADDLE_FLIGHT_AUTOARM",
                   default=_world_size() > 1):
        return None
    recorder.record("auto_arm", where=where)
    return arm()


# the PADDLE_SANITIZE env autostart arms from inside this module's own
# `from . import sanitize` (before the recorder existed) — replay any
# events it buffered so the sanitize_arm event reaches the ring
_sanitize.flush_flight_events()
