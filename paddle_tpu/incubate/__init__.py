"""paddle.incubate (reference: python/paddle/incubate/)."""
from . import nn
from . import autograd
from . import distributed
from . import checkpoint
from . import asp
