"""paddle.incubate (reference: python/paddle/incubate/)."""
from . import nn
from . import autograd
from . import distributed
from . import checkpoint
from . import asp
# reference: python/paddle/incubate/optimizer/{lookahead,modelaverage}
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
