"""Fused multi-tensor optimizer update Pallas kernels.

Reference capability: the multi_tensor / fused optimizer ops
(paddle/fluid/operators/fused/fused_adam_op.cu, merged_momentum_op) —
ONE kernel launch updates every parameter instead of a per-parameter
tree of small fusions.

Design: every parameter's fp32 update base (the master weight under
multi_precision, else the parameter itself), its gradient and its
moment slots are flattened, zero-padded to a chunk multiple and
stacked into ONE (chunks, rows, 128) buffer per role. The kernel grid
walks chunks; per-PARAMETER scalars (Adam bias-correction
denominators, AdamW's per-param decay mask) ride as per-chunk SMEM
scalars so parameters with different restored beta-pow state or an
`apply_decay_param_fun` filter still fuse. The learning rate is a
traced (1, 1) scalar — backoff/growth/schedules never recompile.

Zero padding is update-invariant for every supported rule (0 params,
0 grads, 0 moments stay 0), and unpacking slices the pads away.

`apply_fused(opt, params, grads, state, lr)` is the entry
`Optimizer.apply_gradients` calls under PADDLE_PALLAS_FUSION=1; it
returns None for anything it cannot fuse exactly (unknown rule) and
the caller falls back to the per-parameter loop.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["apply_fused", "fused_adam_chunks", "fused_sgd_chunks",
           "fused_momentum_chunks", "CHUNK_ROWS", "CHUNK_LANES"]

CHUNK_ROWS = 256
CHUNK_LANES = 128
_CHUNK = CHUNK_ROWS * CHUNK_LANES  # 32768 elements / 128 KB f32


# ---------------------------------------------------------------------------
# kernels (one grid step == one chunk)
# ---------------------------------------------------------------------------

def _adam_kernel(lr_ref, d1_ref, d2_ref, wd_ref, p_ref, g_ref, m_ref,
                 v_ref, po_ref, mo_ref, vo_ref, *, b1, b2, eps, wdc):
    lr = lr_ref[0, 0]
    d1 = d1_ref[0, 0]          # 1 - beta1^t (this step's denominator)
    d2 = d2_ref[0, 0]
    p = p_ref[...]
    g = g_ref[...]
    if wdc:
        g = g + wdc * p        # coupled L2 (non-decoupled optimizers)
    wd = wd_ref[0, 0]          # decoupled per-param coeff (AdamW)
    p = p * (1.0 - lr * wd)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    # divide (not multiply-by-reciprocal): bit-identical to the
    # per-parameter Adam._update rule
    mhat = m / d1
    vhat = v / d2
    po_ref[...] = p - lr * mhat / (jnp.sqrt(vhat) + eps)
    mo_ref[...] = m
    vo_ref[...] = v


def _sgd_kernel(lr_ref, p_ref, g_ref, po_ref, *, wdc):
    lr = lr_ref[0, 0]
    p = p_ref[...]
    g = g_ref[...]
    if wdc:
        g = g + wdc * p
    po_ref[...] = p - lr * g


def _momentum_kernel(lr_ref, p_ref, g_ref, v_ref, po_ref, vo_ref, *,
                     mu, nesterov, wdc):
    lr = lr_ref[0, 0]
    p = p_ref[...]
    g = g_ref[...]
    if wdc:
        g = g + wdc * p
    v = v_ref[...] * mu + g
    step = g + mu * v if nesterov else v
    po_ref[...] = p - lr * step
    vo_ref[...] = v


def _scalar_spec():
    return pl.BlockSpec((1, 1), lambda i: (0, 0),
                        memory_space=pltpu.SMEM)


def _chunk_scalar_spec():
    return pl.BlockSpec((1, 1), lambda i: (i, 0),
                        memory_space=pltpu.SMEM)


def _chunk_spec():
    return pl.BlockSpec((1, CHUNK_ROWS, CHUNK_LANES),
                        lambda i: (i, 0, 0), memory_space=pltpu.VMEM)


def _audit_aliases(aliases, ins, out_shape, where):
    """PTA042 audit of a packer's hand-built input_output_aliases
    against the actual operands/results — opt-in (PADDLE_ANALYSIS=1
    or PADDLE_SANITIZE=donation), so a future edit to the pack math
    fails as a named finding instead of an XLA layout error."""
    from ....analysis import enabled as _analysis_enabled
    from ....monitor import sanitize as _sanitize

    if not (_analysis_enabled() or _sanitize._donation):
        return
    from ....analysis.donation import audit_aliases

    outs = (out_shape if isinstance(out_shape, (tuple, list))
            and not hasattr(out_shape, "shape") else (out_shape,))
    report = audit_aliases(
        aliases,
        [tuple(a.shape) for a in ins],
        [tuple(o.shape) for o in outs],
        in_dtypes=[str(a.dtype) for a in ins],
        out_dtypes=[str(o.dtype) for o in outs],
        where=where)
    if report.findings:
        import sys

        for f in report.sorted():
            print(f"[paddle_tpu.analysis] {f.format()}",
                  file=sys.stderr)
        report.record()
        if _sanitize._donation:
            raise ValueError(
                f"PTA042 input_output_aliases audit failed in "
                f"{where}:\n"
                + "\n".join(f.format() for f in report.findings))


def fused_adam_chunks(p, g, m, v, lr, d1, d2, wd, *, beta1, beta2, eps,
                      wd_coupled=0.0, interpret=False):
    """One launch of the fused Adam/AdamW rule over (G, R, 128) chunk
    buffers; d1/d2/wd are (G, 1) per-chunk scalars. Returns
    (new_p, new_m, new_v)."""
    G = p.shape[0]
    # ONE aliases/operands/out_shape triple shared by the audit and
    # the launch — the audit must check exactly what XLA gets
    aliases = {4: 0, 6: 1, 7: 2}
    operands = (lr.reshape(1, 1), d1, d2, wd, p, g, m, v)
    out_shape = (jax.ShapeDtypeStruct(p.shape, p.dtype),) * 3
    _audit_aliases(aliases, operands, out_shape, "fused_adam_chunks")
    kernel = functools.partial(_adam_kernel, b1=beta1, b2=beta2,
                               eps=eps, wdc=wd_coupled)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(G,),
        in_specs=[_scalar_spec(), _chunk_scalar_spec(),
                  _chunk_scalar_spec(), _chunk_scalar_spec(),
                  _chunk_spec(), _chunk_spec(), _chunk_spec(),
                  _chunk_spec()],
        out_specs=(_chunk_spec(),) * 3,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)


def fused_sgd_chunks(p, g, lr, *, wd_coupled=0.0, interpret=False):
    G = p.shape[0]
    aliases = {1: 0}
    operands = (lr.reshape(1, 1), p, g)
    out_shape = jax.ShapeDtypeStruct(p.shape, p.dtype)
    _audit_aliases(aliases, operands, out_shape, "fused_sgd_chunks")
    kernel = functools.partial(_sgd_kernel, wdc=wd_coupled)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(G,),
        in_specs=[_scalar_spec(), _chunk_spec(), _chunk_spec()],
        out_specs=_chunk_spec(),
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)


def fused_momentum_chunks(p, g, v, lr, *, momentum, nesterov=False,
                          wd_coupled=0.0, interpret=False):
    G = p.shape[0]
    aliases = {1: 0, 3: 1}
    operands = (lr.reshape(1, 1), p, g, v)
    out_shape = (jax.ShapeDtypeStruct(p.shape, p.dtype),) * 2
    _audit_aliases(aliases, operands, out_shape,
                   "fused_momentum_chunks")
    kernel = functools.partial(_momentum_kernel, mu=momentum,
                               nesterov=nesterov, wdc=wd_coupled)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(G,),
        in_specs=[_scalar_spec(), _chunk_spec(), _chunk_spec(),
                  _chunk_spec()],
        out_specs=(_chunk_spec(),) * 2,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)


# ---------------------------------------------------------------------------
# pack / unpack
# ---------------------------------------------------------------------------

def _segments(names, params):
    """(name, n_elems, n_chunks) per fused param, in a stable order.
    Zero-size params keep ne=0 (their whole chunk is padding) — the
    pad math below must see the TRUE element count or the stacked
    buffer stops being a chunk multiple."""
    segs = []
    for n in names:
        ne = int(np.prod(np.shape(params[n])))
        segs.append((n, ne, max(1, -(-ne // _CHUNK))))
    return segs


def _pack(segs, arrays):
    """arrays: name -> array (any shape/dtype). Returns the stacked
    f32 (G, R, 128) buffer, zero-padded per segment."""
    flats = []
    for n, ne, nc in segs:
        a = jnp.ravel(arrays[n]).astype(jnp.float32)
        pad = nc * _CHUNK - ne
        if pad:
            a = jnp.pad(a, (0, pad))
        flats.append(a)
    return jnp.concatenate(flats).reshape(-1, CHUNK_ROWS, CHUNK_LANES)


def _pack_scalars(segs, values):
    """Per-param traced/plain scalars -> (G, 1) f32 per-chunk."""
    parts = [jnp.full((nc,), jnp.asarray(values[n], jnp.float32))
             for n, ne, nc in segs]
    return jnp.concatenate(parts).reshape(-1, 1)


def _unpack(segs, buf, shapes):
    out = {}
    flat = buf.reshape(-1)
    off = 0
    for n, ne, nc in segs:
        out[n] = flat[off:off + ne].reshape(shapes[n])
        off += nc * _CHUNK
    return out


# ---------------------------------------------------------------------------
# Optimizer.apply_gradients entry
# ---------------------------------------------------------------------------

def apply_fused(opt, params, grads, state, lr):
    """Fused replacement for the per-parameter apply_gradients loop.
    `grads` is already clipped. Returns (new_params, new_state), or
    None when this optimizer/state shape can't fuse exactly."""
    kind = getattr(opt, "_pallas_fused_kind", None)
    if kind not in ("sgd", "momentum", "adam", "adamw"):
        return None
    from . import interpret_mode, _on_tpu

    interpret = interpret_mode() and not _on_tpu()
    names = [n for n in params if grads.get(n) is not None]
    passthrough = [n for n in params if grads.get(n) is None]
    if not names:
        return dict(params), {n: state[n] for n in state}
    wd = opt._wd_coeff()
    decoupled = bool(getattr(opt, "_decoupled_wd", False))
    wd_coupled = 0.0 if decoupled else float(wd)
    segs = _segments(names, params)
    shapes = {n: np.shape(params[n]) for n in names}
    # update base: fp32 master weight when present (multi_precision),
    # else the parameter itself (computed in f32, cast back)
    masters = {n: state[n].get("master_weight") for n in names}
    base = {n: (masters[n] if masters[n] is not None else params[n])
            for n in names}
    pbuf = _pack(segs, base)
    gbuf = _pack(segs, grads)
    lr32 = jnp.asarray(lr, jnp.float32)

    new_state = {n: dict(state[n]) for n in state}
    if kind in ("adam", "adamw"):
        mbuf = _pack(segs, {n: state[n]["moment1"] for n in names})
        vbuf = _pack(segs, {n: state[n]["moment2"] for n in names})
        d1s, d2s, wds = {}, {}, {}
        fun = getattr(opt, "_apply_decay_param_fun", None)
        for n in names:
            b1p = state[n]["beta1_pow"] * opt._beta1
            b2p = state[n]["beta2_pow"] * opt._beta2
            new_state[n]["beta1_pow"] = b1p
            new_state[n]["beta2_pow"] = b2p
            d1s[n] = 1.0 - b1p
            d2s[n] = 1.0 - b2p
            apply_decay = decoupled and (fun is None or fun(n))
            wds[n] = float(wd) if apply_decay else 0.0
        npbuf, nmbuf, nvbuf = fused_adam_chunks(
            pbuf, gbuf, mbuf, vbuf, lr32,
            _pack_scalars(segs, d1s), _pack_scalars(segs, d2s),
            _pack_scalars(segs, wds), beta1=opt._beta1,
            beta2=opt._beta2, eps=opt._epsilon,
            wd_coupled=wd_coupled, interpret=interpret)
        for n, m in _unpack(segs, nmbuf, shapes).items():
            new_state[n]["moment1"] = m
        for n, v in _unpack(segs, nvbuf, shapes).items():
            new_state[n]["moment2"] = v
    elif kind == "momentum":
        vbuf = _pack(segs, {n: state[n]["velocity"] for n in names})
        npbuf, nvbuf = fused_momentum_chunks(
            pbuf, gbuf, vbuf, lr32, momentum=opt._momentum,
            nesterov=opt._use_nesterov, wd_coupled=wd_coupled,
            interpret=interpret)
        for n, v in _unpack(segs, nvbuf, shapes).items():
            new_state[n]["velocity"] = v
    else:  # sgd
        npbuf = fused_sgd_chunks(pbuf, gbuf, lr32,
                                 wd_coupled=wd_coupled,
                                 interpret=interpret)

    new_base = _unpack(segs, npbuf, shapes)
    new_params = {}
    for n in names:
        if masters[n] is not None:
            new_state[n]["master_weight"] = new_base[n]
            new_params[n] = new_base[n].astype(params[n].dtype)
        else:
            new_params[n] = new_base[n].astype(params[n].dtype)
    for n in passthrough:
        new_params[n] = params[n]
    return new_params, new_state
