"""paddle.incubate.nn.pallas — the fused Pallas TPU kernel library.

Reference capability surface: paddle/fluid/operators/fused/ — the
CUDA fused_bias_dropout_residual_layer_norm / fused_gelu epilogues and
the multi-tensor fused_adam/merged_momentum optimizer kernels. Here
each is ONE Pallas kernel (forward AND backward) instead of a chain of
XLA fusions:

- `layernorm.fused_layer_norm` / `fused_residual_layer_norm`: LayerNorm
  with optional residual-add prologue and GeLU epilogue — one VMEM pass
  over the activation per direction (the unfused composition re-reads
  it once per op).
- `optim.apply_fused`: multi-tensor optimizer update (Adam/AdamW/SGD/
  Momentum) over the flattened parameter set — one kernel launch per
  step instead of a per-parameter tree of fusions.

Everything is OFF by default and numerics-neutral when off:
`PADDLE_PALLAS_FUSION=1` arms the fused paths on TPU backends;
`PADDLE_PALLAS_INTERPRET=1` additionally lets them run through the
Pallas interpreter on CPU (parity tests / debugging — slow, never for
production CPU runs). Every wired call site falls back to the unfused
composition when the kernels are unavailable for a shape/backend.
"""
from __future__ import annotations

import os

__all__ = ["fusion_enabled", "interpret_mode", "kernels_available",
           "ln_supported", "layernorm", "optim", "paged_attention",
           "fused_layer_norm", "fused_residual_layer_norm"]

_TPU_PLATFORMS = ("tpu", "axon")


def _env_on(name, default="0"):
    return os.environ.get(name, default) not in ("0", "", "false",
                                                 "False", "off")


def fusion_enabled():
    """Master switch for the fused-kernel call sites
    (PADDLE_PALLAS_FUSION=1)."""
    return _env_on("PADDLE_PALLAS_FUSION")


def interpret_mode():
    """Run the kernels through the Pallas interpreter
    (PADDLE_PALLAS_INTERPRET=1): CPU parity testing only."""
    return _env_on("PADDLE_PALLAS_INTERPRET")


def _on_tpu():
    import jax

    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except Exception:
        return False


def kernels_available():
    """Fusion armed AND a backend that can run the kernels: a real TPU,
    or the interpreter when explicitly requested."""
    return fusion_enabled() and (_on_tpu() or interpret_mode())


def ln_supported(hidden):
    """Can the fused LayerNorm kernels take this last-dim size here?
    Compiled TPU kernels want a lane-aligned hidden dim; the
    interpreter takes anything (odd-shape parity tests)."""
    if not fusion_enabled():
        return False
    if _on_tpu():
        return hidden % 128 == 0
    return interpret_mode()


# the kernel submodules pull in jax.experimental.pallas (and, on TPU,
# the Mosaic backend) — keep them LAZY so `import paddle_tpu` (which
# reaches here through incubate.nn) doesn't pay that at startup with
# the feature off; call sites go through these attributes, which load
# on first touch (PEP 562)
def __getattr__(name):
    if name in ("layernorm", "optim", "paged_attention"):
        import importlib

        return importlib.import_module("." + name, __name__)
    if name in ("fused_layer_norm", "fused_residual_layer_norm"):
        from . import layernorm

        return getattr(layernorm, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
