"""Fused LayerNorm Pallas kernels (forward AND backward).

Replaces the reference's fused_bias_dropout_residual_layer_norm /
fused layernorm+activation CUDA epilogues
(paddle/fluid/operators/fused/fused_layernorm_residual_dropout_bias.h)
with TPU-native row-tiled kernels:

- `fused_layer_norm(x, w, b)`: LayerNorm over the last dim, optional
  GeLU epilogue (`activation="gelu"`) — the LayerNorm→GeLU pair the
  transformer FFN prologue wants as ONE activation read.
- `fused_residual_layer_norm(x, residual, w, b)`: residual-add →
  LayerNorm, returning BOTH the normalized output and the sum (the
  next block's residual) from one pass.

Statistics (mean / rstd) are computed in f32 and saved for the
backward, which recomputes x̂ from the saved sum — the standard
two-kernel LN autodiff, O(rows) extra memory. Rows are zero-padded to
the block multiple; zero rows contribute exactly nothing to dw/db and
their outputs are sliced off, so padding is bit-neutral.

`interpret=True` (or PADDLE_PALLAS_INTERPRET=1) runs the same kernels
through the Pallas interpreter so parity is testable on CPU, including
odd shapes no real TPU tiling would accept.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_layer_norm", "fused_residual_layer_norm"]

# per-row stats ride a small trailing lane dim (TPU tiling rule: block
# last dim == full array dim) — same layout as attention_pallas
_STAT_LANES = 8

_MAX_BLOCK_ROWS = 256


def _row_block(n):
    """Row-block size: pow2 <= 256; tiny inputs shrink to the next
    pow2 >= n so padding never more than doubles the work."""
    if n >= _MAX_BLOCK_ROWS:
        return _MAX_BLOCK_ROWS
    return max(8, 1 << math.ceil(math.log2(max(1, n))))


def _pad_rows(a, n_pad):
    n = a.shape[0]
    if n == n_pad:
        return a
    return jnp.pad(a, ((0, n_pad - n), (0, 0)))


def _gelu(x, approximate):
    if approximate:
        # tanh form — matches jax.nn.gelu(approximate=True)
        c = math.sqrt(2.0 / math.pi)
        return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))
    return 0.5 * x * (1.0 + jax.lax.erf(x / math.sqrt(2.0)))


def _gelu_grad(x, approximate):
    if approximate:
        c = math.sqrt(2.0 / math.pi)
        u = c * (x + 0.044715 * x * x * x)
        t = jnp.tanh(u)
        du = c * (1.0 + 3.0 * 0.044715 * x * x)
        return 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t * t) * du
    cdf = 0.5 * (1.0 + jax.lax.erf(x / math.sqrt(2.0)))
    pdf = jnp.exp(-0.5 * x * x) / math.sqrt(2.0 * math.pi)
    return cdf + x * pdf


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, res_ref, w_ref, b_ref,
                   y_ref, s_ref, mu_ref, rs_ref, *,
                   eps, act, approx, has_residual):
    x = x_ref[...]
    if has_residual:
        # the sum happens in the INPUT dtype — identical rounding to
        # the unfused `x + residual` the composition performs, so the
        # fused path is numerics-compatible, not just close
        s = x + res_ref[...]
        s_ref[...] = s
    else:
        s = x
        # the placeholder sum output still must be written (an
        # undefined Mosaic output block is UB); every step hits the
        # same (1, H) block
        s_ref[...] = jnp.zeros_like(s_ref)
    sf = s.astype(jnp.float32)
    mu = jnp.mean(sf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(sf - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (sf - mu) * rstd
    y = xhat * w_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    if act == "gelu":
        y = _gelu(y, approx)
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = jnp.broadcast_to(mu, mu_ref.shape)
    rs_ref[...] = jnp.broadcast_to(rstd, rs_ref.shape)


def _ln_fwd_impl(x2, res2, w, b, eps, act, approx, interpret):
    n, h = x2.shape
    bn = _row_block(n)
    n_pad = ((n + bn - 1) // bn) * bn
    grid = n_pad // bn
    xp = _pad_rows(x2, n_pad)
    has_residual = res2 is not None
    rp = _pad_rows(res2, n_pad) if has_residual else \
        jnp.zeros((1, h), x2.dtype)  # placeholder, never read
    row_spec = pl.BlockSpec((bn, h), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    res_spec = row_spec if has_residual else pl.BlockSpec(
        (1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    wb_spec = pl.BlockSpec((1, h), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((bn, _STAT_LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    kernel = functools.partial(_ln_fwd_kernel, eps=eps, act=act,
                               approx=approx, has_residual=has_residual)
    y, s, mu, rs = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n_pad, h), x2.dtype),
                   jax.ShapeDtypeStruct((n_pad, h), x2.dtype)
                   if has_residual
                   else jax.ShapeDtypeStruct((1, h), x2.dtype),
                   jax.ShapeDtypeStruct((n_pad, _STAT_LANES),
                                        jnp.float32),
                   jax.ShapeDtypeStruct((n_pad, _STAT_LANES),
                                        jnp.float32)),
        grid=(grid,),
        in_specs=[row_spec, res_spec, wb_spec, wb_spec],
        out_specs=(row_spec,
                   row_spec if has_residual else wb_spec,
                   stat_spec, stat_spec),
        interpret=interpret,
    )(xp, rp, w.reshape(1, h), b.reshape(1, h))
    return y[:n], (s[:n] if has_residual else None), mu[:n], rs[:n]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _ln_bwd_kernel(dy_ref, ds_ref, s_ref, w_ref, b_ref, mu_ref, rs_ref,
                   dx_ref, dwp_ref, dbp_ref, *,
                   act, approx, has_residual):
    sf = s_ref[...].astype(jnp.float32)
    mu = mu_ref[:, :1]
    rstd = rs_ref[:, :1]
    xhat = (sf - mu) * rstd
    w = w_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    if act == "gelu":
        yln = xhat * w + b_ref[...].astype(jnp.float32)
        dy = dy * _gelu_grad(yln, approx)
    # per-block partial parameter grads; summed across blocks outside
    dwp_ref[...] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbp_ref[...] = jnp.sum(dy, axis=0, keepdims=True)
    dxhat = dy * w
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxhat - m1 - xhat * m2)
    if has_residual:
        # the sum is ALSO an output (next residual): its cotangent
        # joins the LN chain's
        dx = dx + ds_ref[...].astype(jnp.float32)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _ln_bwd_impl(dy2, ds2, s2, w, b, mu, rs, act, approx, interpret):
    n, h = dy2.shape
    bn = _row_block(n)
    n_pad = ((n + bn - 1) // bn) * bn
    grid = n_pad // bn
    has_residual = ds2 is not None
    dyp = _pad_rows(dy2, n_pad)
    dsp = _pad_rows(ds2, n_pad) if has_residual else \
        jnp.zeros((1, h), dy2.dtype)
    sp = _pad_rows(s2, n_pad)
    mup = jnp.pad(mu, ((0, n_pad - n), (0, 0)))
    rsp = jnp.pad(rs, ((0, n_pad - n), (0, 0)))
    row_spec = pl.BlockSpec((bn, h), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    ds_spec = row_spec if has_residual else pl.BlockSpec(
        (1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    wb_spec = pl.BlockSpec((1, h), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    part_spec = pl.BlockSpec((1, h), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((bn, _STAT_LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    kernel = functools.partial(_ln_bwd_kernel, act=act, approx=approx,
                               has_residual=has_residual)
    dx, dwp, dbp = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n_pad, h), dy2.dtype),
                   jax.ShapeDtypeStruct((grid, h), jnp.float32),
                   jax.ShapeDtypeStruct((grid, h), jnp.float32)),
        grid=(grid,),
        in_specs=[row_spec, ds_spec, row_spec, wb_spec, wb_spec,
                  stat_spec, stat_spec],
        out_specs=(row_spec, part_spec, part_spec),
        interpret=interpret,
    )(dyp, dsp, sp, w.reshape(1, h), b.reshape(1, h), mup, rsp)
    dw = jnp.sum(dwp, axis=0).astype(w.dtype)
    db = jnp.sum(dbp, axis=0).astype(b.dtype)
    return dx[:n], dw, db


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------

def _resolve_interpret(interpret):
    if interpret is not None:
        return bool(interpret)
    from . import interpret_mode, _on_tpu

    return interpret_mode() and not _on_tpu()


def _to2d(x):
    h = x.shape[-1]
    return x.reshape(-1, h), x.shape


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_layer_norm(x, weight, bias, eps=1e-5, activation=None,
                     approximate=True, interpret=None):
    """y = [gelu](LayerNorm(x) * weight + bias) over the last dim."""
    y, _, _, _ = _ln_fn_fwd_impl(x, weight, bias, eps, activation,
                                 approximate, interpret)
    return y


def _ln_fn_fwd_impl(x, weight, bias, eps, activation, approximate,
                    interpret):
    itp = _resolve_interpret(interpret)
    x2, shape = _to2d(x)
    y, _, mu, rs = _ln_fwd_impl(x2, None, weight, bias, eps, activation,
                                approximate, itp)
    return y.reshape(shape), x2, mu, rs


def _ln_fn_fwd(x, weight, bias, eps, activation, approximate, interpret):
    y, x2, mu, rs = _ln_fn_fwd_impl(x, weight, bias, eps, activation,
                                    approximate, interpret)
    return y, (x2, weight, bias, mu, rs, x.shape)


def _ln_fn_bwd(eps, activation, approximate, interpret, res, dy):
    x2, weight, bias, mu, rs, shape = res
    itp = _resolve_interpret(interpret)
    dy2 = dy.reshape(x2.shape)
    dx, dw, db = _ln_bwd_impl(dy2, None, x2, weight, bias, mu, rs,
                              activation, approximate, itp)
    return dx.reshape(shape).astype(dy.dtype), dw, db


fused_layer_norm.defvjp(_ln_fn_fwd, _ln_fn_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_residual_layer_norm(x, residual, weight, bias, eps=1e-5,
                              activation=None, approximate=True,
                              interpret=None):
    """s = x + residual; y = [gelu](LayerNorm(s) * weight + bias).

    Returns (y, s) — the fused_bias_dropout_residual_layer_norm shape
    of epilogue: one pass produces both the normalized activation and
    the carried residual sum."""
    (y, s), _ = _ln_res_fwd(x, residual, weight, bias, eps, activation,
                            approximate, interpret)
    return y, s


def _ln_res_fwd(x, residual, weight, bias, eps, activation, approximate,
                interpret):
    itp = _resolve_interpret(interpret)
    x2, shape = _to2d(x)
    r2, _ = _to2d(residual)
    y, s, mu, rs = _ln_fwd_impl(x2, r2, weight, bias, eps, activation,
                                approximate, itp)
    return ((y.reshape(shape), s.reshape(shape)),
            (s, weight, bias, mu, rs, shape))


def _ln_res_bwd(eps, activation, approximate, interpret, res, cts):
    dy, ds = cts
    s2, weight, bias, mu, rs, shape = res
    itp = _resolve_interpret(interpret)
    dy2 = dy.reshape(s2.shape)
    ds2 = ds.reshape(s2.shape)
    dx, dw, db = _ln_bwd_impl(dy2, ds2, s2, weight, bias, mu, rs,
                              activation, approximate, itp)
    dx = dx.reshape(shape).astype(dy.dtype)
    # d/dx (x + residual) is identity into both inputs
    return dx, dx, dw, db


fused_residual_layer_norm.defvjp(_ln_res_fwd, _ln_res_bwd)
