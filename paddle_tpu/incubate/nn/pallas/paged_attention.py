"""Pallas TPU ragged paged-attention decode kernel.

The serving-side sibling of `attention_pallas.py` (PR 8): that kernel
streams contiguous K/V tiles for TRAINING-shaped batches; this one
reads K/V through per-request BLOCK TABLES out of the paged pools
(`inference.serving.kv_cache`), so ONE launch covers every sequence
in a continuous-batching decode step at mixed context lengths — the
Ragged Paged Attention design (PAPERS.md arxiv 2604.15464).

Decode shape: one query token per sequence.

    q            [B, H, D]           this step's query rows
    k/v pool     [N, BS, H, D]       one layer's paged pool
    block_tables [B, MAXB] int32     pool block id per (seq, slot)
    context_lens [B]       int32     real tokens per sequence

Multi-query decode (`paged_attention_multi`): the speculative-decode
verify dispatch feeds T consecutive query tokens per sequence — q is
[B, T, H, D], query slot `t` of sequence `b` sits at absolute
position `context_lens[b] - 1 + t` and may attend over
`context_lens[b] + t` tokens (itself included). Same grid, same
block streaming: the per-slot causal offset is a compile-time
constant (the T-loop is python-unrolled, T <= 8), so one launch
verifies a whole draft window per sequence with per-slot position
masking instead of T separate dispatches.

Grid: (B, MAXB). `block_tables`/`context_lens` ride as SCALAR
PREFETCH arguments (pltpu.PrefetchScalarGridSpec) so the K/V
BlockSpec index maps resolve `tables[b, j]` BEFORE the kernel body —
the DMA engine fetches exactly the blocks each sequence owns, in
table order, nothing else. Dead blocks (slots past the sequence's
context length) are grid-skipped with `pl.when`, the pad-and-mask
discipline the PR-8 flash kernel established: a fully-dead block
costs its (skipped) grid step, never a matmul; the tail block masks
`k_pos >= context_len` scores to -inf so padded slots contribute
exactly zero weight. Online softmax (running max/denominator in VMEM
scratch) accumulates across a sequence's blocks, so nothing
[S, S]-shaped ever materializes.

`interpret=True` runs the same kernel through the Pallas interpreter
for CPU parity tests (the PR-8 contract; see
`paged_attention_reference` for the dense gather it must match).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_attention", "paged_attention_reference",
           "paged_attention_multi", "paged_attention_multi_reference",
           "paged_decode_supported"]

_NEG_INF = -1e30
# running max / denominator ride (H, _STAT_LANES) f32 scratch — the
# small-lane stats layout attention_pallas.py uses
_STAT_LANES = 8


def paged_decode_supported(head_dim, block_size):
    """Can the compiled TPU kernel take this geometry here? The MXU
    wants lane-aligned reduction dims; the interpreter (CPU parity)
    takes anything."""
    from . import interpret_mode, kernels_available

    if not kernels_available():
        return False
    if interpret_mode():
        return True
    return head_dim in (64, 128) and block_size % 8 == 0


def _paged_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, sm_scale, block_size,
                  num_slots):
    b = pl.program_id(0)
    j = pl.program_id(1)
    ctx = lens_ref[b]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # grid-skip dead blocks: table slots at or past the context hold
    # NULL_BLOCK padding — no matmul, no softmax update
    @pl.when(j * block_size < ctx)
    def _step():
        q = q_ref[0]                                   # [H, D]
        k = jnp.transpose(k_ref[0], (1, 0, 2))         # [H, BS, D]
        v = jnp.transpose(v_ref[0], (1, 0, 2))         # [H, BS, D]
        # s[h, t] = q[h, :] . k[h, t, :] — operands stay in the pool
        # dtype (bf16-native MXU), statistics f32 (the PR-8 rule)
        s = jax.lax.dot_general(
            q[:, None, :], k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)[:, 0, :]
        s = s * sm_scale                               # [H, BS]
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < ctx, s, _NEG_INF)
        m_prev = m_ref[:, :1]                          # [H, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # [H, BS]
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype)[:, None, :], v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)[:, 0, :]
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_slots - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, block_tables, context_lens,
                    sm_scale=1.0, interpret=False):
    """Ragged paged-attention decode: one launch, all sequences."""
    b, h, d = q.shape
    n, bs, hk, dk = k_pool.shape
    if (hk, dk) != (h, d):
        raise ValueError(
            f"pool heads/dim {(hk, dk)} != query {(h, d)}")
    maxb = block_tables.shape[1]
    kernel = functools.partial(
        _paged_kernel, sm_scale=sm_scale, block_size=bs,
        num_slots=maxb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, j, bt, cl: (i, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d),
                               lambda i, j, bt, cl: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, _STAT_LANES), jnp.float32),
            pltpu.VMEM((h, _STAT_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(context_lens, jnp.int32), q, k_pool, v_pool)


def paged_attention_reference(q, k_pool, v_pool, block_tables,
                              context_lens, sm_scale=1.0):
    """Dense gather reference — the math the kernel must match, and
    the engine's CPU fallback. Mirrors the training `_attention`
    softmax exactly (f32 scores, -1e30 mask, softmax, cast, PV) so a
    paged decode step reproduces the full re-forward loop's tokens."""
    seq_k = k_pool[block_tables]           # [B, MAXB, BS, H, D]
    seq_v = v_pool[block_tables]
    b, maxb, bs, h, d = seq_k.shape
    seq_k = seq_k.reshape(b, maxb * bs, h, d)
    seq_v = seq_v.reshape(b, maxb * bs, h, d)
    s = jnp.einsum("bhd,bshd->bhs", q, seq_k,
                   preferred_element_type=jnp.float32) * sm_scale
    mask = jnp.arange(maxb * bs)[None, :] < context_lens[:, None]
    s = jnp.where(mask[:, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhs,bshd->bhd", p, seq_v)


# ---------------------------------------------------------------------------
# multi-query decode slots (speculative-decode verification)
# ---------------------------------------------------------------------------

def _paged_multi_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref,
                        o_ref, acc_ref, m_ref, l_ref, *, sm_scale,
                        block_size, num_slots, num_q):
    """Per (sequence, table slot) grid step over T query slots. The
    scratch stacks the T slots' online-softmax state along the
    sublane axis (rows [t*H, (t+1)*H)); the T-loop is python-unrolled
    so every per-slot causal offset is a constant."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    ctx0 = lens_ref[b]               # tokens visible to query slot 0
    h = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # the deepest slot sees ctx0 + num_q - 1 tokens; blocks past that
    # are dead for EVERY slot and grid-skip like the single-query
    # kernel. Shallower slots mask the block's tail per-position.
    @pl.when(j * block_size < ctx0 + num_q - 1)
    def _step():
        k = jnp.transpose(k_ref[0], (1, 0, 2))         # [H, BS, D]
        v = jnp.transpose(v_ref[0], (1, 0, 2))
        for t in range(num_q):
            ctx = ctx0 + t
            q = q_ref[0, t]                            # [H, D]
            s = jax.lax.dot_general(
                q[:, None, :], k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)[:, 0, :]
            s = s * sm_scale                           # [H, BS]
            k_pos = j * block_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            # a block entirely past THIS slot's context masks to all
            # -inf: p underflows to zero and alpha to one, so the
            # slot's accumulated state passes through untouched
            s = jnp.where(k_pos < ctx, s, _NEG_INF)
            m_prev = m_ref[t * h:(t + 1) * h, :1]      # [H, 1]
            l_prev = l_ref[t * h:(t + 1) * h, :1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=1,
                                             keepdims=True)
            acc_ref[t * h:(t + 1) * h, :] = (
                acc_ref[t * h:(t + 1) * h, :] * alpha
                + jax.lax.dot_general(
                    p.astype(v.dtype)[:, None, :], v,
                    (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)[:, 0, :])
            m_ref[t * h:(t + 1) * h, :] = jnp.broadcast_to(
                m_new, (h, m_ref.shape[1]))
            l_ref[t * h:(t + 1) * h, :] = jnp.broadcast_to(
                l_new, (h, l_ref.shape[1]))

    @pl.when(j == num_slots - 1)
    def _finish():
        for t in range(num_q):
            l = jnp.maximum(l_ref[t * h:(t + 1) * h, :1], 1e-30)
            o_ref[0, t] = (acc_ref[t * h:(t + 1) * h, :]
                           / l).astype(o_ref.dtype)


def paged_attention_multi(q, k_pool, v_pool, block_tables,
                          context_lens, sm_scale=1.0,
                          interpret=False):
    """Multi-query ragged paged-attention: q [B, T, H, D], slot t of
    sequence b attends `context_lens[b] + t` tokens (per-slot causal
    masking over the SAME block table). One launch verifies a whole
    speculative window; T must be small (the slot loop unrolls)."""
    b, t, h, d = q.shape
    if t > 8:
        raise ValueError(
            f"paged_attention_multi unrolls the slot loop — T={t} "
            "query slots > 8 would bloat the kernel; use the dense "
            "reference for long windows")
    n, bs, hk, dk = k_pool.shape
    if (hk, dk) != (h, d):
        raise ValueError(
            f"pool heads/dim {(hk, dk)} != query {(h, d)}")
    maxb = block_tables.shape[1]
    kernel = functools.partial(
        _paged_multi_kernel, sm_scale=sm_scale, block_size=bs,
        num_slots=maxb, num_q=t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, t, h, d),
                         lambda i, j, bt, cl: (i, 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, t, h, d),
                               lambda i, j, bt, cl: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t * h, d), jnp.float32),
            pltpu.VMEM((t * h, _STAT_LANES), jnp.float32),
            pltpu.VMEM((t * h, _STAT_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, t, h, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(context_lens, jnp.int32), q, k_pool, v_pool)


def paged_attention_multi_reference(q, k_pool, v_pool, block_tables,
                                    context_lens, sm_scale=1.0):
    """Dense multi-query reference: the verify math the kernel must
    match, the engine's CPU fallback, AND the prefix-cache tail
    prefill's attention (slot t at absolute position
    context_lens[b] - 1 + t sees context_lens[b] + t tokens — the
    same convention for both uses)."""
    seq_k = k_pool[block_tables]           # [B, MAXB, BS, H, D]
    seq_v = v_pool[block_tables]
    b, maxb, bs, h, d = seq_k.shape
    t = q.shape[1]
    seq_k = seq_k.reshape(b, maxb * bs, h, d)
    seq_v = seq_v.reshape(b, maxb * bs, h, d)
    s = jnp.einsum("bthd,bshd->bths", q, seq_k,
                   preferred_element_type=jnp.float32) * sm_scale
    pos = jnp.arange(maxb * bs)[None, None, :]
    ctx = context_lens[:, None, None] \
        + jnp.arange(t)[None, :, None]     # [B, T, 1]
    mask = pos < ctx                       # [B, T, S]
    s = jnp.where(mask[:, :, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bths,bshd->bthd", p, seq_v)
