"""paddle.incubate.nn — fused transformer blocks (reference:
python/paddle/incubate/nn/layer/fused_transformer.py:25,216,348)."""
from . import attention
from .layer.fused_transformer import (
    FusedMultiHeadAttention,
    FusedFeedForward,
    FusedTransformerEncoderLayer,
)
from . import functional
