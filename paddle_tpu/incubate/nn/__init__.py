"""paddle.incubate.nn — fused transformer blocks (reference:
python/paddle/incubate/nn/layer/fused_transformer.py:25,216,348) and
the Pallas fused-kernel library (paddle_tpu.incubate.nn.pallas)."""
from . import attention
from . import pallas
from .layer.fused_transformer import (
    FusedMultiHeadAttention,
    FusedFeedForward,
    FusedTransformerEncoderLayer,
)
from . import functional
