"""paddle.incubate.nn.functional (reference:
python/paddle/incubate/nn/functional/)."""
from ..attention import scaled_dot_product_attention
from ....nn.functional import (
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)


def fused_multi_head_attention(*args, **kwargs):
    raise NotImplementedError(
        "use incubate.nn.FusedMultiHeadAttention (layer API)")


def fused_feedforward(*args, **kwargs):
    raise NotImplementedError("use incubate.nn.FusedFeedForward (layer API)")
