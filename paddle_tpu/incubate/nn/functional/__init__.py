"""paddle.incubate.nn.functional (reference:
python/paddle/incubate/nn/functional/)."""
from ..attention import scaled_dot_product_attention
from ....nn.functional import (
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)


def fused_multi_head_attention(*args, **kwargs):
    raise NotImplementedError(
        "use incubate.nn.FusedMultiHeadAttention (layer API)")


def fused_feedforward(*args, **kwargs):
    raise NotImplementedError("use incubate.nn.FusedFeedForward (layer API)")


def _ln_fallback(x, weight, bias, epsilon, activation, approximate,
                 residual):
    from ....nn import functional as F

    if residual is not None:
        x = x + residual
    s = x
    h = x.shape[-1]
    y = F.layer_norm(x, [h], weight, bias, epsilon)
    if activation == "gelu":
        from ....ops.activation import gelu

        y = gelu(y, approximate=approximate)
    return y, s


def fused_layer_norm(x, weight, bias, epsilon=1e-5, activation=None,
                     approximate=True, residual=None,
                     return_residual_sum=False):
    """Tensor-level fused LayerNorm with optional residual-add
    prologue and GeLU epilogue (reference:
    fused_bias_dropout_residual_layer_norm / fused layernorm+act).

    Under PADDLE_PALLAS_FUSION=1 on a supporting backend this is ONE
    Pallas kernel per direction (incubate.nn.pallas.layernorm); the
    unfused composition runs otherwise, so calling it is always safe.
    With `residual`, `return_residual_sum=True` also returns the sum
    (the next block's residual) computed in the same pass."""
    if activation not in (None, "gelu"):
        raise ValueError(
            f"fused_layer_norm: activation={activation!r} "
            "(expected None or 'gelu')")
    from ....core.engine import apply_op
    from .. import pallas as _pallas

    h = int(x.shape[-1])
    use_pallas = (_pallas.ln_supported(h)
                  and weight is not None and bias is not None
                  and int(weight.shape[0]) == h)
    if use_pallas:
        if residual is not None:
            def k_res(xv, rv, wv, bv, eps, act, approx):
                y, s = _pallas.fused_residual_layer_norm(
                    xv, rv, wv, bv, eps, act, approx)
                return (y, s)

            y, s = apply_op("fused_residual_layer_norm", k_res, x,
                            residual, weight, bias, eps=float(epsilon),
                            act=activation, approx=bool(approximate))
        else:
            def k_ln(xv, wv, bv, eps, act, approx):
                return _pallas.fused_layer_norm(xv, wv, bv, eps, act,
                                                approx)

            y = apply_op("fused_layer_norm", k_ln, x, weight, bias,
                         eps=float(epsilon), act=activation,
                         approx=bool(approximate))
            s = x
    else:
        y, s = _ln_fallback(x, weight, bias, epsilon, activation,
                            approximate, residual)
    if return_residual_sum:
        return y, s
    return y


def fused_layer_norm_gelu(x, weight, bias, epsilon=1e-5,
                          approximate=True):
    """y = gelu(LayerNorm(x) * weight + bias) — the LayerNorm→GeLU
    pair as one fused kernel (one activation read per direction)."""
    return fused_layer_norm(x, weight, bias, epsilon,
                            activation="gelu", approximate=approximate)


def fused_residual_layer_norm(x, residual, weight, bias, epsilon=1e-5,
                              activation=None, approximate=True):
    """(y, s): s = x + residual, y = [gelu](LayerNorm(s)) — the
    residual-add → LayerNorm epilogue in one pass."""
    return fused_layer_norm(x, weight, bias, epsilon,
                            activation=activation,
                            approximate=approximate, residual=residual,
                            return_residual_sum=True)
