from . import fused_transformer
