"""Fused transformer layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py:25,216,348 over
fused_attention_op.cu / fused_feedforward_op.cu).

TPU-native: "fusion" = one jitted region per block; attention core is
the Pallas flash kernel. The residual+layernorm epilogues (and the
pre-LN prologues) route through incubate.nn.functional.fused_layer_norm
— under PADDLE_PALLAS_FUSION=1 that is the fused Pallas kernel
(incubate.nn.pallas.layernorm, the reference's
fused_bias_dropout_residual_layer_norm analog), and the plain XLA
composition otherwise."""
from __future__ import annotations

import numpy as np

from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.initializer import Constant, XavierNormal
from ....nn.layer.layers import Layer

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        # fused qkv weight: [3, H, D, E] layout in reference; we keep
        # [E, 3E] for a single MXU matmul
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=qkv_weight_attr,
            default_initializer=XavierNormal())
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=XavierNormal())
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)
        self._epsilon = epsilon

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ....ops.manipulation import reshape, transpose, split

        from ... import nn as _inn

        residual = query
        x = query
        if self.normalize_before:
            x = _inn.functional.fused_layer_norm(
                x, self.pre_ln_scale, self.pre_ln_bias, self._epsilon)
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        qkv = transpose(qkv, [2, 0, 3, 1, 4])  # [3, B, H, S, D]
        q, k, v = qkv[0], qkv[1], qkv[2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training)
        out = transpose(out, [0, 2, 1, 3])
        out = reshape(out, [b, s, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        if not self.normalize_before:
            # residual-add -> LayerNorm in one fused pass
            out = _inn.functional.fused_layer_norm(
                out, self.ln_scale, self.ln_bias, self._epsilon,
                residual=residual)
        else:
            out = residual + out
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 name=None):
        super().__init__()
        self._d_model = d_model
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                  else act_dropout_rate)
        self._act = activation
        self._normalize_before = normalize_before
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=XavierNormal())
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=XavierNormal())
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=Constant(1.0), attr=ln1_scale_attr)
        self.ln1_bias = self.create_parameter([d_model], is_bias=True,
                                              attr=ln1_bias_attr)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=Constant(1.0), attr=ln2_scale_attr)
        self.ln2_bias = self.create_parameter([d_model], is_bias=True,
                                              attr=ln2_bias_attr)

    def forward(self, src, cache=None):
        from ... import nn as _inn
        from ....ops import activation as A

        residual = src
        if self._normalize_before:
            src = _inn.functional.fused_layer_norm(
                src, self.ln1_scale, self.ln1_bias, self._epsilon)
        act = getattr(A, self._act)
        out = F.linear(src, self.linear1_weight, self.linear1_bias)
        out = F.dropout(act(out), self._act_dropout_rate,
                        training=self.training)
        out = F.linear(out, self.linear2_weight, self.linear2_bias)
        out = F.dropout(out, self._dropout_rate, training=self.training)
        if not self._normalize_before:
            out = _inn.functional.fused_layer_norm(
                out, self.ln2_scale, self.ln2_bias, self._epsilon,
                residual=residual)
        else:
            out = residual + out
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)
