"""Fused scaled-dot-product attention.

Parity target: the reference's fused_attention CUDA stack
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h).

TPU-native design: a Pallas flash-attention kernel (attention_pallas.py)
for the TPU hot path — tiled over (block_q, block_kv) with online
softmax so the [S, S] score matrix never hits HBM — with an XLA
fallback that relies on compiler fusion (still strong on TPU for
moderate sequence lengths). Selection is automatic by platform.
"""
from __future__ import annotations


import numpy as np
import jax
import jax.numpy as jnp

from ...core.engine import apply_op
from ...core.tensor import Tensor
from ...ops import random as _random


def _xla_attention(q, k, v, mask, scale, causal, dropout_p, key):
    # q,k,v: [B, H, Sq/Skv, D]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * scale
    if mask is not None:
        logits = logits + mask.astype(logits.dtype)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    probs = probs.astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _use_pallas(q_shape, dtype, has_mask, dropout_p):
    try:
        dev = jax.devices()[0].platform
    except Exception:
        return False
    if dev not in ("tpu", "axon"):
        return False
    if dropout_p > 0.0 or has_mask:
        return False  # pallas kernel currently covers causal/full paths
    b, h, s, d = q_shape
    return s >= 128 and d in (64, 128, 256) and s % 128 == 0


def _k_sdpa(q, k, v, mask, scale, causal, dropout_p, key, try_pallas):
    if try_pallas:
        try:
            from .attention_pallas import flash_attention

            return flash_attention(q, k, v, causal=causal, sm_scale=scale)
        except Exception:
            pass
    return _xla_attention(q, k, v, mask, scale, causal, dropout_p, key)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """query/key/value: [B, H, S, D] (callers reshape). Returns same."""
    d = query.shape[-1]
    sm_scale = scale if scale is not None else 1.0 / float(np.sqrt(d))
    dp = dropout_p if training else 0.0
    rng = _random.next_key() if dp > 0.0 else None
    try_pallas = _use_pallas(tuple(query.shape), query.dtype,
                             attn_mask is not None, dp)
    return apply_op("scaled_dot_product_attention", _k_sdpa, query, key,
                    value, attn_mask, scale=sm_scale, causal=bool(is_causal),
                    dropout_p=float(dp), key=rng, try_pallas=try_pallas)
