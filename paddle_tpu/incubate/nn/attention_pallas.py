"""Pallas TPU flash-attention kernel.

Replaces the reference's fused CUDA attention
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h) with a
TPU-native tiled kernel: online-softmax over KV tiles held in VMEM, so
the [S, S] score matrix never materializes in HBM; QK^T and PV ride the
MXU in fp32 accumulation. Forward is Pallas; backward is a custom-VJP
recompute in XLA (einsum chain, fully fused) — flash backward kernel is
a planned upgrade.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256

_NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, block_q,
               block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [BQ, D]
    bq, d = q.shape
    num_kv = seq_k // block_k

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)

    if causal:
        # only iterate kv blocks at-or-below this q block's diagonal
        upper = jnp.minimum(num_kv, (qi + 1) * block_q // block_k
                            + (1 if block_q % block_k else 0))
        upper = jnp.maximum(upper, 1)
        acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    else:
        acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc0, m0, l0))

    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    kernel = functools.partial(_fa_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=bq, block_k=bk, seq_k=sk)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d)


def _attn_ref(q, k, v, causal, sm_scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return p, jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, sm_scale=1.0,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    return _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _bwd(causal, sm_scale, block_q, block_k, res, do):
    q, k, v = res
    p, _ = _attn_ref(q, k, v, causal, sm_scale)
    p = p.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = p * (dp - delta) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
