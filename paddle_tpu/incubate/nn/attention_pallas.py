"""Pallas TPU flash-attention kernels (forward AND backward).

Replaces the reference's fused CUDA attention
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h) with
TPU-native tiled kernels: online-softmax over KV tiles streamed through
VMEM, so neither the [S, S] score matrix nor full K/V ever sit in VMEM
at once; QK^T and PV ride the MXU with fp32 accumulation.

- forward: grid (batch*heads, q_blocks, kv_blocks); KV tiles are
  streamed per grid step (block shape (1, block_k, d)) and the output
  accumulator/running-max/denominator live in VMEM scratch. The
  logsumexp per query row is written out for the backward pass.
- backward: two kernels. dq iterates (bh, q_blocks, kv_blocks)
  accumulating dq in scratch; dk/dv iterates (bh, kv_blocks, q_blocks)
  accumulating dk and dv. Both recompute probabilities from q,k and the
  saved logsumexp — the standard flash-attention backward, O(S) memory.
- `interpret=True` runs the same kernels through the Pallas interpreter
  so correctness is testable on CPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# r5 on-chip sweep (benchmarks/attn_bench.py, B=4 H=16 S=1024 D=64,
# fwd+bwd): (1024,1024) 1.22 ms beats (256,256) 2.50 ms, (512,512)
# 3.40 ms, jax's reference TPU pallas kernel 4.48 ms and XLA dense
# 8.25 ms — per-grid-step overhead dominates KV streaming at these
# sizes, so prefer the largest block that fits VMEM (the [bq,bk] f32
# score tile is the biggest buffer: 1024^2*4 = 4 MB of ~16 MB).
# _pick_block still drops to divisors of shorter sequences, and long
# sequences tile at 1024 with the causal block skip.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024

# per-row stats (lse/delta) ride a trailing lane dim; 8 satisfies the
# TPU tiling rule (block last dim == full array dim) at 16x less HBM
# than the 128-lane layout
_STAT_LANES = 8


def _pick_block(seq, preferred):
    """Largest power-of-two block <= preferred that divides seq, or
    None when the sequence needs padding (no pow2 divisor >= 8)."""
    for cand in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if cand <= preferred and cand <= seq and seq % cand == 0:
            return cand
    return None


def _block_and_pad(seq, preferred):
    """(block, padded_seq). Divisor-free lengths (a 129-token prompt,
    a ragged tail microbatch) pad UP to the next multiple of the
    largest power-of-two block <= min(preferred, seq): the kernels
    mask padded KV positions to -inf (exactly zero attention weight)
    and padded q rows are sliced off, so the unpadded region is
    bit-identical to an unpadded run — see _mask_scores."""
    b = _pick_block(seq, preferred)
    if b is not None:
        return b, seq
    b = 8
    while b * 2 <= min(preferred, seq):
        b *= 2
    return b, ((seq + b - 1) // b) * b

_NEG_INF = -1e30


def _mask_scores(s, qi, ki, block_q, block_k, causal, kv_len):
    """Causal and/or padded-KV masking of one score tile. kv_len is
    the REAL key length; positions >= kv_len are padding and score
    -inf (exp underflows to exactly 0 — padded keys contribute
    nothing, bit-exactly). kv_len=None means no padding."""
    if not causal and kv_len is None:
        return s
    bq, bk = s.shape
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
        ok = q_pos >= k_pos
        if kv_len is not None:
            ok = jnp.logical_and(ok, k_pos < kv_len)
    else:
        ok = k_pos < kv_len
    return jnp.where(ok, s, _NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   acc_ref, m_ref, l_ref, *,
                   sm_scale, causal, block_q, block_k, num_kv,
                   kv_len=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: blocks strictly above the diagonal contribute nothing;
    # fully-padded KV blocks (past the real key length) likewise
    run = (qi + 1) * block_q > ki * block_k if causal else True
    if kv_len is not None:
        kv_run = ki * block_k < kv_len
        run = kv_run if run is True else jnp.logical_and(run, kv_run)

    @pl.when(run)
    def _step():
        # keep matmul OPERANDS in the input dtype (bf16): the MXU is
        # bf16-native with f32 accumulation — casting q/k/v up to f32
        # before the dots ran the matmuls on the slow f32 path (r5).
        # Softmax statistics stay f32 (preferred_element_type).
        q = q_ref[0]                                      # [BQ, D]
        k = k_ref[0]                                      # [BK, D]
        v = v_ref[0]                                      # [BK, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, kv_len)
        m_prev = m_ref[:, :1]                             # [BQ, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_kv - 1)
    def _finish():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            m + jnp.log(jnp.maximum(l, 1e-30)), lse_ref.shape[1:])


def _pad_seq(a, s_pad):
    s = a.shape[1]
    if s == s_pad:
        return a
    return jnp.pad(a, ((0, 0), (0, s_pad - s), (0, 0)))


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                    interpret=False):
    # the kernels run matmuls on the operands' own dtype (bf16-native
    # MXU, f32 accumulation) — promote mixed inputs to one dtype here
    # so a bf16 q with an f32 KV cache doesn't die inside the kernel
    # (and silently fall back to dense through callers' try/except)
    ct = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype), v.dtype)
    q, k, v = q.astype(ct), k.astype(ct), v.astype(ct)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, sq_pad = _block_and_pad(sq, block_q)
    bk, sk_pad = _block_and_pad(sk, block_k)
    kv_len = sk if sk_pad != sk else None
    num_kv = sk_pad // bk
    qr = _pad_seq(q.reshape(b * h, sq, d), sq_pad)
    kr = _pad_seq(k.reshape(b * h, sk, d), sk_pad)
    vr = _pad_seq(v.reshape(b * h, sk, d), sk_pad)
    kernel = functools.partial(
        _fa_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=bq, block_k=bk, num_kv=num_kv, kv_len=kv_len)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((b * h, sq_pad, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, sq_pad, _STAT_LANES),
                                        jnp.float32)),
        grid=(b * h, sq_pad // bq, num_kv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _STAT_LANES),
                         lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return (out[:, :sq].reshape(b, h, sq, d),
            lse[:, :sq, 0].reshape(b, h, sq))


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_acc, *,
                      sm_scale, causal, block_q, block_k, num_kv,
                      kv_len=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = (qi + 1) * block_q > ki * block_k if causal else True
    if kv_len is not None:
        kv_run = ki * block_k < kv_len
        run = kv_run if run is True else jnp.logical_and(run, kv_run)

    @pl.when(run)
    def _step():
        # bf16 matmul operands, f32 accumulation/statistics (see fwd)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                           # [BQ, 1]
        delta = delta_ref[0][:, :1]                       # [BQ, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, kv_len)
        p = jnp.exp(s - lse)                              # [BQ, BK]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_kv - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc, *,
                       sm_scale, causal, block_q, block_k, num_q,
                       kv_len=None):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (qi + 1) * block_q > ki * block_k if causal else True
    if kv_len is not None:
        kv_run = ki * block_k < kv_len
        run = kv_run if run is True else jnp.logical_and(run, kv_run)

    @pl.when(run)
    def _step():
        # bf16 matmul operands, f32 accumulation/statistics (see fwd)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                           # [BQ, 1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, kv_len)
        p = jnp.exp(s - lse)                              # [BQ, BK]
        pb = p.astype(do.dtype)
        # dv_j += p^T @ do
        dv_acc[...] += jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        # dk_j += ds^T @ q
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, do, causal, sm_scale,
                    block_q, block_k, interpret=False):
    ct = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype),
                           jnp.promote_types(v.dtype, do.dtype))
    q, k, v, do = (q.astype(ct), k.astype(ct), v.astype(ct),
                   do.astype(ct))
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, sq_pad = _block_and_pad(sq, block_q)
    bk, sk_pad = _block_and_pad(sk, block_k)
    kv_len = sk if sk_pad != sk else None
    num_q = sq_pad // bq
    num_kv = sk_pad // bk
    qr = _pad_seq(q.reshape(b * h, sq, d), sq_pad)
    kr = _pad_seq(k.reshape(b * h, sk, d), sk_pad)
    vr = _pad_seq(v.reshape(b * h, sk, d), sk_pad)
    dor = _pad_seq(do.reshape(b * h, sq, d), sq_pad)
    # per-row stats ride a small trailing lane dim (TPU block tiling).
    # Padded q rows carry lse=0 with do=0, so every gradient
    # contribution they could make is exactly 0 (see _block_and_pad)
    lser = jnp.broadcast_to(
        _pad_seq(lse.reshape(b * h, sq)[:, :, None], sq_pad),
        (b * h, sq_pad, _STAT_LANES))
    # delta_i = rowsum(do_i * o_i) — cheap fused elementwise + reduce
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(b * h, sq)
    delta = jnp.broadcast_to(
        _pad_seq(delta[:, :, None], sq_pad),
        (b * h, sq_pad, _STAT_LANES))

    q_spec = pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                          memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, bq, _STAT_LANES),
                            lambda bh, qi, ki: (bh, qi, 0),
                            memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=bq, block_k=bk,
                          num_kv=num_kv, kv_len=kv_len),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_pad, d), q.dtype),
        grid=(b * h, num_q, num_kv),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    # dkv grid: (bh, kv_blocks, q_blocks) — q streams innermost
    q_spec2 = pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0),
                           memory_space=pltpu.VMEM)
    k_spec2 = pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0),
                           memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, bq, _STAT_LANES),
                             lambda bh, ki, qi: (bh, qi, 0),
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=bq, block_k=bk,
                          num_q=num_q, kv_len=kv_len),
        out_shape=(jax.ShapeDtypeStruct((b * h, sk_pad, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk_pad, d), v.dtype)),
        grid=(b * h, num_kv, num_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2,
                  row_spec2],
        out_specs=(k_spec2, k_spec2),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    return (dq[:, :sq].reshape(b, h, sq, d),
            dk[:, :sk].reshape(b, h, sk, d),
            dv[:, :sk].reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------

def _attn_ref(q, k, v, causal, sm_scale):
    """Dense reference (testing / tiny shapes only — O(S^2) HBM)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return p, jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, sm_scale=1.0,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    out, _ = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                             interpret)
    return out


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, causal, sm_scale,
                                 block_q, block_k, interpret)
    # custom_vjp contract: cotangents match the PRIMAL dtypes even
    # when mixed inputs were promoted inside the impl
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


flash_attention.defvjp(_fwd, _bwd)
