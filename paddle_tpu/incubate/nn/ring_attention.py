"""Ring attention — sequence/context-parallel attention over the 'sp'
mesh axis.

Capability target: SURVEY §5 requires long-context SP/CP as a
first-class axis (the reference snapshot predates it — its ceiling is
fused/sparse attention, `paddle/fluid/operators/fused/fmha_ref.h`).
Extension-point pattern: `fleet/base/topology.py:117` (the 'sep' axis
in our HybridCommunicateGroup).

TPU-native design (Ring Attention / "How to Scale Your Model" recipe):
queries stay put, K/V blocks rotate around the sp ring via
`lax.ppermute` (XLA collective-permute over ICI neighbors — no
all-gather, so per-chip memory stays O(S/sp)). Each of the sp steps
combines the local partial attention with flash-style online-softmax
accumulation (running max m, denominator l, accumulator acc), so the
result is EXACT attention over the full sequence. XLA overlaps each
step's ppermute with the next step's matmuls (the scan body issues the
permute before the compute consumes the previous block).

Use `ring_attention_shard` inside an existing shard_map; use
`ring_attention` on global arrays (it builds the shard_map island —
also valid inside jit, composing with GSPMD-partitioned surroundings).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...distributed import mesh as mesh_mod

__all__ = ["ring_attention", "ring_attention_shard",
           "ulysses_attention"]


def _chunk_attn_partial(q, k_blk, v_blk, q_off, k_off, causal, sm_scale):
    """Partial (unnormalized) attention of local q against one KV block
    at global offset k_off. Returns (scores_max, exp_scores_sum, pv)
    per flash-attention bookkeeping. Shapes: q [b,h,sq,d],
    k_blk/v_blk [b,h,sk,d]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[2], k_blk.shape[2]
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos >= k_pos, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)                # [b,h,sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    # bf16 matmul operands, f32 accumulation — same MXU policy as the
    # flash kernels (r5); statistics stay f32
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
                    preferred_element_type=jnp.float32)
    return m, l, pv


def ring_attention_shard(q, k, v, axis_name="sp", causal=True,
                         sm_scale=None):
    """Exact attention over the full (sp-sharded) sequence; call inside
    shard_map. q/k/v: per-shard [b, h, s_local, d]."""
    # psum of a Python literal over a named axis folds to the static
    # ring size at trace time
    nsteps = int(lax.psum(1, axis_name))
    my = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    qf = q  # bf16-native MXU: operands stay in input dtype (r5)
    q_off = my * s_local
    perm = [(j, (j + 1) % nsteps) for j in range(nsteps)]

    def combine(carry, i, k_blk, v_blk):
        acc, m, l = carry
        # this block originated at rank (my - i) mod sp
        k_off = ((my - i) % nsteps) * s_local
        m_cur, l_cur, pv = _chunk_attn_partial(
            qf, k_blk, v_blk, q_off, k_off, causal, sm_scale)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_cur - m_new)
        return (acc * alpha + pv * beta, m_new, l * alpha + l_cur * beta)

    def step(carry, i):
        acc, m, l, k_blk, v_blk = carry
        acc, m, l = combine((acc, m, l), i, k_blk, v_blk)
        # rotate KV to the next neighbor (ICI ring); the permute's input
        # doesn't depend on this step's matmuls, so XLA overlaps them
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (acc, m, l, k_blk, v_blk), None

    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    m0 = jnp.full((b, h, s_local, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    carry = (acc0, m0, l0, k, v)
    if nsteps > 1:
        # scan the first nsteps-1 blocks (each ends with a rotation)…
        carry, _ = lax.scan(step, carry, jnp.arange(nsteps - 1))
    # …and fold in the final block without a wasted trailing permute
    acc, m, l, k_blk, v_blk = carry
    acc, m, l = combine((acc, m, l), nsteps - 1, k_blk, v_blk)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _dense_causal_attention(q, k, v, causal, sm_scale):
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _sp_mesh_or_none(mesh, seq_axis):
    """Resolve the live mesh for sequence parallelism; None means
    'no sp axis > 1 — fall back to exact dense attention'."""
    mesh = mesh or mesh_mod.get_mesh()
    if (mesh is None or seq_axis not in mesh.shape
            or mesh.shape[seq_axis] <= 1):
        return None
    return mesh


def _pick_axis(mesh, a, dim):
    """Use mesh axis `a` for a tensor dim only when it exists, is >1,
    and divides the dim."""
    return a if (a in mesh.shape and mesh.shape[a] > 1
                 and dim % mesh.shape[a] == 0) else None


def _shard_map(body, mesh, in_specs, out_specs):
    """The cross-version shard_map shim, shared with linalg.dist
    (distributed.mesh.shard_map_compat)."""
    from ...distributed.mesh import shard_map_compat

    return shard_map_compat(body, mesh, in_specs, out_specs)


def ulysses_attention(q, k, v, causal=True, sm_scale=None, mesh=None,
                      batch_axis="dp", head_axis="mp", seq_axis="sp"):
    """Ulysses/DeepSpeed-style sequence parallelism (SURVEY §5:
    "Ulysses-style head-sharded alltoall"): inputs arrive sharded over
    the SEQUENCE dim; one all_to_all re-shards them over the HEAD dim
    (each sp-rank then holds h/sp full-sequence heads), attention runs
    LOCALLY and exactly (any kernel — here the dense/flash path), and a
    second all_to_all restores sequence sharding.

    Two all_to_alls of the qkv/out tensors vs ring's sp ppermutes of
    KV — Ulysses wins when heads >> sp and attention is kernel-bound;
    ring wins on memory for extreme sequence lengths. Requires
    num_heads % sp == 0.

    Host-emulation note: earlier XLA:CPU builds could deadlock when
    this cross-module all_to_all overlapped other collectives at large
    head counts (concurrent-thunk rendezvous ordering races). The
    current runtime is clean — tests/test_ring_attention.py pins the
    previously-failing shapes (heads up to 64 inside the hybrid dp×sp
    train step) as active regression tests."""
    mesh = _sp_mesh_or_none(mesh, seq_axis)
    if mesh is None:
        return _dense_causal_attention(q, k, v, causal, sm_scale)
    sp = mesh.shape[seq_axis]
    b, h, s, d = q.shape
    if h % sp or s % sp:
        return _dense_causal_attention(q, k, v, causal, sm_scale)

    bax = _pick_axis(mesh, batch_axis, b)
    # heads may ALSO stay sharded over the tensor-parallel axis: the
    # island's local all_to_all then splits the per-mp-rank head count
    # by sp, which requires h % (mp * sp) == 0; otherwise heads
    # replicate over mp inside the island (correct, just redundant)
    mp_n = mesh.shape.get(head_axis, 1)
    hax = (head_axis if (head_axis in mesh.shape and mp_n > 1
                         and h % (mp_n * sp) == 0) else None)
    in_spec = P(bax, hax, seq_axis, None)   # seq-sharded in/out
    out_spec = in_spec

    def body(qs, ks, vs):
        # [b, h, s/sp, d] per rank -> tiled all_to_all: scatter the
        # HEAD dim, gather the SEQ dim -> [b, h/sp, s, d] full-sequence
        # heads; the inverse swap restores sequence sharding.
        def seq2head(x):
            return lax.all_to_all(x, seq_axis, split_axis=1,
                                  concat_axis=2, tiled=True)

        def head2seq(x):
            return lax.all_to_all(x, seq_axis, split_axis=2,
                                  concat_axis=1, tiled=True)

        qh, kh, vh = seq2head(qs), seq2head(ks), seq2head(vs)
        oh = _dense_causal_attention(qh, kh, vh, causal, sm_scale)
        return head2seq(oh)

    return _shard_map(body, mesh, (in_spec, in_spec, in_spec),
                      out_spec)(q, k, v)


def ring_attention(q, k, v, causal=True, sm_scale=None, mesh=None,
                   batch_axis="dp", head_axis="mp", seq_axis="sp"):
    """Global-array entry: shard_map island over (batch_axis, head_axis,
    seq_axis). q/k/v: [b, h, s, d] global. Valid inside jit — GSPMD
    reshards surroundings to match. Falls back to single-shard exact
    attention when the mesh has no sp axis > 1."""
    mesh = _sp_mesh_or_none(mesh, seq_axis)
    if mesh is None:
        return _dense_causal_attention(q, k, v, causal, sm_scale)
    if q.shape[2] % mesh.shape[seq_axis]:
        return _dense_causal_attention(q, k, v, causal, sm_scale)
    spec = P(_pick_axis(mesh, batch_axis, q.shape[0]),
             _pick_axis(mesh, head_axis, q.shape[1]),
             seq_axis, None)
    body = functools.partial(ring_attention_shard, axis_name=seq_axis,
                             causal=causal, sm_scale=sm_scale)
    return _shard_map(body, mesh, (spec, spec, spec), spec)(q, k, v)
