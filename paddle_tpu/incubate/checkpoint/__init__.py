"""paddle.incubate.checkpoint namespace."""
from . import auto_checkpoint
from . import elastic
from .elastic import CheckpointManager  # noqa: F401
