"""paddle.incubate.checkpoint namespace."""
from . import auto_checkpoint
