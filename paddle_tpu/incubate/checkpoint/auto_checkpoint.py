"""Auto checkpoint / resume (reference:
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py —
AutoCheckpointChecker:71 + train_epoch_range:598).

Contract replicated: `for epoch in train_epoch_range(N): ...` is
epoch-granular auto save/restore keyed by job id — on a fresh run it
yields 0..N-1 and checkpoints registered models/optimizers each epoch;
after a crash+relaunch with the same PADDLE_JOB_ID it restores state
and resumes from the first incomplete epoch. The reference stores to
HDFS; here the FS abstraction (fleet/utils/fs.py LocalFS) writes a
local/NFS dir from PADDLE_CHECKPOINT_DIR."""
from __future__ import annotations

import json
import os
import time

__all__ = ["train_epoch_range", "register", "clear_registry",
           "checkpoint_dir", "job_id", "save_checkpoint",
           "load_checkpoint"]

_registered = []  # (name, obj-with-state_dict/set_state_dict)


def job_id():
    return os.environ.get("PADDLE_JOB_ID", "default_job")


def checkpoint_dir():
    d = os.environ.get("PADDLE_CHECKPOINT_DIR",
                       os.path.join(".", "auto_checkpoint"))
    return os.path.join(d, job_id())


def register(name, obj):
    """Register a model/optimizer (anything with state_dict /
    set_state_dict) for auto checkpointing."""
    _registered.append((name, obj))
    return obj


def clear_registry():
    _registered.clear()


def _meta_path():
    return os.path.join(checkpoint_dir(), "meta.json")


def save_checkpoint(epoch):
    from ... import framework

    d = checkpoint_dir()
    os.makedirs(d, exist_ok=True)
    for name, obj in _registered:
        framework.save(obj.state_dict(), os.path.join(d, name + ".pd"))
    tmp = _meta_path() + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"epoch": epoch, "ts": time.time(),
                   "names": [n for n, _ in _registered]}, f)
    os.replace(tmp, _meta_path())  # atomic: crash-safe metadata


def load_checkpoint():
    """Returns the last completed epoch (or -1) after restoring the
    registered objects."""
    from ... import framework

    if not os.path.exists(_meta_path()):
        return -1
    with open(_meta_path()) as f:
        meta = json.load(f)
    d = checkpoint_dir()
    for name, obj in _registered:
        p = os.path.join(d, name + ".pd")
        if os.path.exists(p):
            obj.set_state_dict(framework.load(p))
    return int(meta.get("epoch", -1))


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1):
    """reference train_epoch_range:598 — resumable epoch generator."""
    last_done = load_checkpoint()
    for epoch in range(last_done + 1, max_epoch_num):
        yield epoch
        if (epoch + 1) % max(save_checkpoint_inter, 1) == 0 \
                or epoch == max_epoch_num - 1:
            save_checkpoint(epoch)
