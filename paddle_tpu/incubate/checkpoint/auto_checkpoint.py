"""Auto checkpoint / resume — job-keyed, fault-tolerant epoch ranges.

Parity target: python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py (AutoCheckpointChecker:71 env contract,
train_epoch_range:598, TrainEpochRange save/restore over the FS
abstraction, time-based save interval, checkpoint rotation).

Contract replicated (r3 weak #6 — the previous 88-line shim kept only
the epoch loop):

  * `AutoCheckpointChecker` reads the SAME env contract: the feature
    gates on PADDLE_RUNNING_ENV == PADDLE_EDL_AUTO_CHECKPOINT (plus
    job id / checkpoint path / trainer id / save interval), so ported
    launch configs work; without the gate the range degrades to a
    plain epoch loop (the reference behavior). PADDLE_CHECKPOINT_DIR
    set explicitly also enables (local-dir convenience).
  * ranges are NAMED: two `train_epoch_range` loops in one job
    checkpoint independently (the reference's running-key).
  * saves rotate: the newest `max_checkpoint_num` epoch snapshots are
    kept, and restore falls back to the NEWEST VALID one — a crash
    mid-save (torn files) costs one interval, not the job.
  * saves fire on an epoch interval AND a TIME interval
    (save_checkpoint_inter seconds, reference default 900) —
    long epochs still checkpoint.
  * storage goes through the fleet FS abstraction (fleet/utils/fs.py
    LocalFS; an HDFS-like client with the same interface plugs in),
    and only trainer 0 writes while every trainer restores.
"""
from __future__ import annotations

import json
import os
import pickle
import time

__all__ = ["AutoCheckpointChecker", "train_epoch_range", "register",
           "clear_registry", "checkpoint_dir", "job_id",
           "save_checkpoint", "load_checkpoint"]

_registered = []  # (name, obj-with-state_dict/set_state_dict)


class AutoCheckpointChecker:
    """Env-contract reader (reference AutoCheckpointChecker:71)."""

    def __init__(self):
        self.run_env = os.getenv("PADDLE_RUNNING_ENV")
        self.job_id = os.getenv("PADDLE_JOB_ID", "default_job")
        self.trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self.checkpoint_path = os.getenv(
            "PADDLE_CHECKPOINT_DIR",
            os.getenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH",
                      os.path.join(".", "auto_checkpoint")))
        self.save_checkpoint_inter = int(os.getenv(
            "PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900"))
        self.max_checkpoint_num = int(os.getenv(
            "PADDLE_EDL_MAX_CHECKPOINT_NUM", "2"))

    @property
    def enabled(self):
        """The reference gates the whole feature on the EDL env; a
        plain run gets a plain epoch loop."""
        return (self.run_env == "PADDLE_EDL_AUTO_CHECKPOINT"
                or "PADDLE_CHECKPOINT_DIR" in os.environ)

    def job_dir(self):
        return os.path.join(self.checkpoint_path, self.job_id)


def job_id():
    return AutoCheckpointChecker().job_id


def checkpoint_dir():
    return AutoCheckpointChecker().job_dir()


def register(name, obj):
    """Register a model/optimizer (anything with state_dict /
    set_state_dict) for auto checkpointing."""
    _registered.append((name, obj))
    return obj


def clear_registry():
    _registered.clear()


def _fs():
    from ...distributed.fleet.utils.fs import LocalFS

    return LocalFS()


class _Range:
    """One named resumable range (reference TrainEpochRange)."""

    def __init__(self, name, checker=None):
        self.checker = checker or AutoCheckpointChecker()
        self.name = name
        self.dir = os.path.join(self.checker.job_dir(), name)
        self._last_save_t = time.time()

    # -- layout: <job>/<range>/epoch_<N>/{meta.json, <name>.pd...} ----
    def _epoch_dir(self, epoch):
        return os.path.join(self.dir, f"epoch_{epoch}")

    def _snapshots(self):
        fs = _fs()
        if not fs.is_exist(self.dir):
            return []
        dirs, _files = fs.ls_dir(self.dir)
        out = []
        for base in dirs:
            if base.startswith("epoch_"):
                try:
                    out.append(int(base[len("epoch_"):]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, epoch):
        if self.checker.trainer_id != 0:
            return  # the reference: only trainer 0 writes
        from ... import framework

        d = self._epoch_dir(epoch)
        tmp = d + ".tmp"
        fs = _fs()
        if fs.is_exist(tmp):
            fs.delete(tmp)
        os.makedirs(tmp, exist_ok=True)
        for name, obj in _registered:
            framework.save(obj.state_dict(),
                           os.path.join(tmp, name + ".pd"))
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"epoch": epoch, "ts": time.time(),
                       "names": [n for n, _ in _registered],
                       "complete": True}, f)
        if fs.is_exist(d):
            fs.delete(d)
        os.replace(tmp, d)  # atomic publish: no torn snapshots
        self._last_save_t = time.time()
        # rotate: keep only the newest max_checkpoint_num
        snaps = self._snapshots()
        for old in snaps[:-self.checker.max_checkpoint_num]:
            fs.delete(self._epoch_dir(old))

    def restore(self):
        """Restore from the NEWEST VALID snapshot; returns its epoch
        or -1. Invalid/torn snapshots are skipped (crash mid-save)."""
        from ... import framework

        for epoch in reversed(self._snapshots()):
            d = self._epoch_dir(epoch)
            meta_p = os.path.join(d, "meta.json")
            try:
                with open(meta_p) as f:
                    meta = json.load(f)
                if not meta.get("complete"):
                    continue
                for name, obj in _registered:
                    p = os.path.join(d, name + ".pd")
                    if os.path.exists(p):
                        obj.set_state_dict(framework.load(p))
                return int(meta["epoch"])
            except (OSError, ValueError, KeyError, EOFError,
                    pickle.UnpicklingError):
                # torn snapshot — try the previous one. A truncated
                # .pd raises UnpicklingError (or EOFError at the very
                # start of the stream), neither of which the original
                # OSError/ValueError/KeyError net caught: the restore
                # died on exactly the crash it existed to survive.
                continue
        return -1

    def due(self, epoch, save_inter_epochs, max_epoch_num):
        """Save on the epoch interval, on the LAST epoch, or when the
        time interval elapsed (reference save_checkpoint_inter)."""
        if epoch == max_epoch_num - 1:
            return True
        if (epoch + 1) % max(save_inter_epochs, 1) == 0:
            return True
        return (time.time() - self._last_save_t
                >= self.checker.save_checkpoint_inter)


# module-level convenience wrappers (shim-API back-compat)
def save_checkpoint(epoch, name="default_range"):
    _Range(name).save(epoch)


def load_checkpoint(name="default_range"):
    return _Range(name).restore()


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1,
                      name="default_range"):
    """Resumable epoch generator (reference train_epoch_range:598):

        for epoch in train_epoch_range(90):
            train_one_epoch()

    Fresh job: yields 0..N-1, snapshotting the registered objects.
    Relaunch with the same PADDLE_JOB_ID: restores the newest valid
    snapshot and resumes from the first incomplete epoch. Disabled
    (no env contract): a plain range."""
    checker = AutoCheckpointChecker()
    if not checker.enabled:
        yield from range(max_epoch_num)
        return
    rng = _Range(name, checker)
    last_done = rng.restore()
    for epoch in range(last_done + 1, max_epoch_num):
        yield epoch
        if rng.due(epoch, save_checkpoint_inter, max_epoch_num):
            rng.save(epoch)
