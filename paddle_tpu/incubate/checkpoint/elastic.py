"""Elastic fault-tolerant training checkpoints (ROADMAP item 4).

Parity target: the EDL auto-checkpoint contract
(incubate/checkpoint/auto_checkpoint.py) grown into a real
elastic-training subsystem. `auto_checkpoint` stays the epoch-granular,
registration-based port of the reference; this module adds what a
preemptible TPU pod actually needs — a kill -9 mid-fit costs minutes,
not the job:

  * FULL training-state snapshots — not just registered state_dicts:
    model params + buffers, live optimizer slots (read off the donated
    buffers TrainStepCompiler.adopt_state_from already shares,
    captured at a step boundary so donation can't hand us invalidated
    arrays), the rng key + counter, LR-scheduler state, and the
    epoch/step cursors that let the DataLoader fast-forward its
    sampler on restore.

  * ASYNC + SHARDED writes — save() hands a host snapshot to a
    background writer thread (latest-wins: a slow disk drops the
    intermediate snapshot, never blocks the step loop); under a live
    multi-process mesh each rank writes only its addressable shards
    and the manifest records every array's PartitionSpec layout, so
    restore reassembles the global host array and the (possibly
    RESHAPED) mesh re-shards it on first dispatch. Counters
    ckpt/{saves,async_inflight,write_us,bytes,dropped,errors,
    emergency_saves,restores} + ckpt_write flight spans make the
    writer watchdog-visible (a wedged checkpoint FS shows up as a
    stuck ckpt_write op, not a silent stall).

  * WATCHDOG checkpoint-then-abort + preemption — arm() registers an
    incident hook with monitor.flight: when the collective watchdog
    fires, the manager writes a best-effort step-boundary checkpoint
    NEXT TO the flight bundle; install_preemption_handler() chains
    onto SIGTERM (PADDLE_CKPT_PREEMPT_SIGNAL) so a preemption notice
    sets `preempted` (Model.fit checkpoints synchronously at the next
    step boundary and stops) while a background thread writes the
    flight "preempt" bundle plus an emergency snapshot in case no
    boundary is ever reached.

Snapshot layout (rotated, newest `max_num` kept):

    <dir>/step_<G>/state_rank<r>.pd   per-rank pickle (host arrays or
                                      addressable-shard pieces)
    <dir>/step_<G>/manifest.json      written LAST by rank 0 (atomic
                                      tmp+replace): the completeness
                                      marker + cursor + array specs

`dir` defaults to the EDL env contract:
<PADDLE_CKPT_DIR|PADDLE_CHECKPOINT_DIR|PADDLE_EDL_HDFS_CHECKPOINT_PATH
|./auto_checkpoint>/<PADDLE_JOB_ID>/train_state — relaunching with the
same PADDLE_JOB_ID finds the snapshots.

The manager is tree-generic: it stores/merges any nested
dict/list/tuple of arrays. hapi.Model owns WHAT goes in a snapshot
(Model._training_state) and Model.fit(resume=...) owns applying it.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import pickle
import signal
import threading
import time

import numpy as np
import jax

from ...core import monitor as _cmon
from ...core.tensor import Tensor
from ...monitor import chaos as _chaos
from ...monitor import flight as _flight
from ...monitor import sanitize as _sanitize
from ...monitor.flight import _env_float, _env_int, _env_on

__all__ = ["CheckpointManager", "SCHEMA", "default_checkpoint_dir"]

SCHEMA = "paddle_tpu.ckpt/1"


def default_checkpoint_dir(name="train_state"):
    """EDL env contract -> snapshot directory (same root resolution
    as auto_checkpoint.AutoCheckpointChecker, one subdir deeper so
    epoch ranges and training-state snapshots never collide)."""
    root = (os.environ.get("PADDLE_CKPT_DIR")
            or os.environ.get("PADDLE_CHECKPOINT_DIR")
            or os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH")
            or os.path.join(".", "auto_checkpoint"))
    job = os.environ.get("PADDLE_JOB_ID", "default_job")
    return os.path.join(root, job, name)


def _rank():
    try:
        from ...distributed.env import peek_rank

        return int(peek_rank())
    except Exception:
        return 0


def _world_size():
    try:
        from ...distributed.env import peek_world_size

        return int(peek_world_size())
    except Exception:
        return 1


def _mesh_axes():
    try:
        from ...distributed import mesh as mesh_mod

        m = mesh_mod.get_mesh()
        return {k: int(v) for k, v in m.shape.items()} if m is not None \
            else None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# host snapshot trees (hostify / shard / merge)
# ---------------------------------------------------------------------------

def _spec_of(arr):
    """JSON-able PartitionSpec of a jax array (None when unsharded /
    single-device)."""
    try:
        from jax.sharding import NamedSharding

        sh = getattr(arr, "sharding", None)
        if isinstance(sh, NamedSharding):
            return [list(a) if isinstance(a, (tuple, list)) else a
                    for a in sh.spec]
    except Exception:
        pass
    return None


def _shard_pieces(arr):
    """This process's unique addressable pieces of a non-fully-
    addressable array: [(normalized index, host array), ...]."""
    pieces = []
    for s in arr.addressable_shards:
        if s.replica_id != 0:
            continue  # replicas: one writer per distinct piece
        idx = [list(sl.indices(dim)[:2])
               for sl, dim in zip(s.index, arr.shape)]
        # np.array, not asarray: an owned copy (asarray of a CPU jax
        # array is a zero-copy VIEW of the device buffer)
        pieces.append((idx, np.array(s.data)))
    return pieces


def _hostify(obj, specs, path=""):
    """Device tree -> host snapshot tree. jax arrays come off device
    as owned numpy copies (fully addressable) or as shard-piece dicts
    (multi-process); every NamedSharding spec is recorded in `specs`
    keyed by tree path so the manifest carries the layout."""
    if isinstance(obj, Tensor):
        return _hostify(obj._value, specs, path)
    if isinstance(obj, jax.Array):
        spec = _spec_of(obj)
        if spec is not None:
            specs[path] = {"shape": [int(d) for d in obj.shape],
                           "dtype": str(obj.dtype), "spec": spec}
        if getattr(obj, "is_fully_addressable", True):
            # np.array, NOT asarray: on the CPU backend asarray is a
            # zero-copy view of the live device buffer — the next
            # dispatch's donation would mutate the "snapshot" while
            # the async writer (or the _last emergency fallback) is
            # still holding it
            return np.array(obj)
        return {"__sharded__": True,
                "shape": [int(d) for d in obj.shape],
                "dtype": str(obj.dtype), "spec": spec,
                "pieces": _shard_pieces(obj)}
    if isinstance(obj, np.ndarray):
        return np.array(obj)  # own it: the source may mutate later
    if isinstance(obj, dict):
        return {k: _hostify(v, specs, f"{path}/{k}")
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_hostify(v, specs, f"{path}/{i}")
                 for i, v in enumerate(obj))
    return obj


def _is_sharded_leaf(obj):
    return isinstance(obj, dict) and obj.get("__sharded__") is True


def _merge_trees(trees):
    """Merge per-rank snapshot trees: sharded leaves reassemble into
    one global host array from every rank's pieces; everything else
    takes rank 0's value. Raises KeyError when pieces don't cover the
    full array (a missing rank file) — restore() then falls back to
    the previous snapshot."""
    base = trees[0]
    if _is_sharded_leaf(base):
        shape = tuple(base["shape"])
        out = np.empty(shape, dtype=np.dtype(base["dtype"]))
        filled = np.zeros(shape, dtype=bool) if out.size else None
        for t in trees:
            for idx, piece in t.get("pieces", []):
                sl = tuple(slice(a, b) for a, b in idx)
                out[sl] = piece
                if filled is not None:
                    filled[sl] = True
        if filled is not None and not filled.all():
            raise KeyError("sharded array has uncovered regions "
                           "(missing rank shard files)")
        return out
    if isinstance(base, dict):
        return {k: _merge_trees([t[k] for t in trees]) for k in base}
    if isinstance(base, (list, tuple)):
        return type(base)(_merge_trees([t[i] for t in trees])
                          for i in range(len(base)))
    return base


def _tree_nbytes(obj):
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if _is_sharded_leaf(obj):
        return sum(int(p.nbytes) for _, p in obj.get("pieces", []))
    if isinstance(obj, dict):
        return sum(_tree_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_tree_nbytes(v) for v in obj)
    return 0


def _atomic_write_bytes(path, payload):
    from ...framework import _atomic_write

    _atomic_write(path, lambda f: f.write(payload))


# torn-snapshot exception set: everything a truncated/corrupt pickle
# or manifest can raise (incl. pickle.UnpicklingError and EOFError —
# the two a bare OSError/ValueError/KeyError net lets escape)
_TORN = (OSError, ValueError, KeyError, EOFError,
         pickle.UnpicklingError)


class CheckpointManager:
    """Async, sharded, rotated training-state snapshots with
    preemption/watchdog emergency saves. See the module docstring for
    the on-disk layout and env contract.

    Cadence (`due(global_step)`): every `save_steps` steps when > 0
    (PADDLE_CKPT_SAVE_STEPS), else every `save_interval_s` seconds
    (PADDLE_CKPT_INTERVAL_S, default PADDLE_EDL_SAVE_CHECKPOINT_INTER
    = 900). Rotation keeps the newest `max_num` snapshots
    (PADDLE_CKPT_MAX_NUM, default PADDLE_EDL_MAX_CHECKPOINT_NUM = 2).
    `async_write` (PADDLE_CKPT_ASYNC, default on) routes save()
    through the background writer; sync=True (or preemption) writes
    on the calling thread."""

    def __init__(self, dir=None, name="train_state", save_steps=None,
                 save_interval_s=None, max_num=None, async_write=None):
        self.dir = dir or default_checkpoint_dir(name)
        if save_steps is None:
            save_steps = _env_int("PADDLE_CKPT_SAVE_STEPS", 0)
        self.save_steps = max(0, int(save_steps))
        if save_interval_s is None:
            save_interval_s = _env_float(
                "PADDLE_CKPT_INTERVAL_S",
                _env_float("PADDLE_EDL_SAVE_CHECKPOINT_INTER", 900.0))
        self.save_interval_s = float(save_interval_s)
        if max_num is None:
            max_num = _env_int("PADDLE_CKPT_MAX_NUM",
                               _env_int("PADDLE_EDL_MAX_CHECKPOINT_NUM",
                                        2))
        self.max_num = max(1, int(max_num))
        if async_write is None:
            async_write = _env_on("PADDLE_CKPT_ASYNC", True)
        self.async_write = bool(async_write)
        self.rank = _rank()
        self.world_size = _world_size()
        self.global_step = 0     # completed optimizer microsteps
        self.cursor = None       # set by restore(): where to resume
        self.preempted = threading.Event()
        # sanitize-aware primitives (PADDLE_SANITIZE=locks): plain
        # threading objects when disarmed, instrumented wrappers
        # feeding the PTA060 lock-order graph when armed
        self._cv = _sanitize.condition("ckpt.cv")
        self._pending = None     # latest-wins (host_tree, meta) slot
        self._busy = False
        self._writer = None
        self._closed = False
        self._last = None        # newest captured (host_tree, meta)
        self._durable_step = -1  # newest step actually on disk
        self._last_save_t = time.monotonic()
        self._state_provider = None
        self._prev_sig = None
        self._preempt_thread = None
        self._preempt_grace_s = 10.0  # window for the loop's own save
        self._lock_timeout_s = 15.0   # bounded waits vs wedged writer
        self._closing = threading.Event()  # close() in progress
        self._write_lock = _sanitize.lock("ckpt.writer")  # vs emergency

    # -- cadence ----------------------------------------------------------
    def due(self, global_step):
        if self.preempted.is_set():
            return True
        if self.save_steps > 0:
            return global_step % self.save_steps == 0
        if (time.monotonic() - self._last_save_t
                < self.save_interval_s):
            return False
        if self.world_size > 1:
            # multi-rank time cadence: every rank must pick the SAME
            # step for its shard or the snapshot is torn (rank 0's
            # manifest at step G, another rank's shard at G+1).
            # Saves reset every rank's timer at the same step, so
            # clocks stay aligned to within one step's skew —
            # quantizing the decision to every 8th step makes the
            # interval flip at the same boundary on all ranks.
            # (Step-based PADDLE_CKPT_SAVE_STEPS is exactly aligned;
            # prefer it for pod-scale jobs.)
            return global_step % 8 == 0
        return True

    def maybe_save(self, state_fn, epoch=0, step_in_epoch=0,
                   global_step=None, sync=False):
        g = self.global_step if global_step is None else int(global_step)
        if not self.due(g):
            return False
        self.save(state_fn(), epoch=epoch, step_in_epoch=step_in_epoch,
                  global_step=g, sync=sync)
        return True

    # -- save path --------------------------------------------------------
    def save(self, state, epoch=0, step_in_epoch=0, global_step=None,
             sync=False):
        """Snapshot `state` (nested dict/list/tuple of Tensors / jax /
        numpy arrays) for step `global_step`. The device->host copy
        happens HERE (step boundary: the arrays are this step's live
        outputs, not donated-in-flight buffers); serialization + disk
        happen on the writer thread unless sync."""
        g = self.global_step if global_step is None else int(global_step)
        specs = {}
        host = _hostify(state, specs)
        if _sanitize._donation:
            # PTA043: verify the hostified snapshot OWNS its memory —
            # a zero-copy view of a live device buffer (the PR-6
            # np.asarray bug) would be mutated by the next dispatch's
            # donation while the writer still holds it
            host = _sanitize.verify_host_tree(
                host, site="ckpt.save", what="checkpoint snapshot")
        meta = {"schema": SCHEMA, "step": g, "epoch": int(epoch),
                "step_in_epoch": int(step_in_epoch),
                "ts": round(time.time(), 3),
                "world_size": self.world_size,
                "mesh": _mesh_axes(), "arrays": specs,
                "complete": True}
        with self._cv:
            self._last = (host, meta)
        self._last_save_t = time.monotonic()
        if sync or not self.async_write:
            try:
                # bounded lock wait when a writer thread exists: the
                # preemption boundary save runs on the fit MAIN
                # thread — a writer wedged on a hung checkpoint FS
                # must not turn checkpoint-then-stop into a hang
                self._write_snapshot(
                    host, meta,
                    lock_timeout=(self._lock_timeout_s
                                  if self.async_write else None))
            except Exception as e:
                # best-effort like the writer path: a full disk /
                # wedged-lock timeout on the preemption boundary save
                # must not crash the fit out of checkpoint-then-stop
                _cmon.stat_add("ckpt/errors", 1)
                _flight.record("ckpt_error",
                               error=f"{type(e).__name__}: {e}"[:200])
            return
        self._ensure_writer()
        with self._cv:
            if self._pending is not None:
                # latest wins: never queue behind a slow disk
                _cmon.stat_add("ckpt/dropped", 1)
            self._pending = (host, meta)
            _cmon.stat_set("ckpt/async_inflight", 1)
            self._cv.notify_all()

    def _ensure_writer(self):
        if self._writer is not None and self._writer.is_alive():
            return
        self._closed = False
        self._writer = threading.Thread(
            target=self._writer_loop, name="paddle-ckpt-writer",
            daemon=True)
        self._writer.start()

    def _writer_loop(self):
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait()
                if self._pending is None:
                    return  # closed and drained
                item, self._pending = self._pending, None
                self._busy = True
            try:
                self._write_snapshot(*item)
            except Exception as e:
                _cmon.stat_add("ckpt/errors", 1)
                _flight.record("ckpt_error",
                               error=f"{type(e).__name__}: {e}"[:200])
            finally:
                with self._cv:
                    self._busy = False
                    _cmon.stat_set("ckpt/async_inflight",
                                   int(self._pending is not None))
                    self._cv.notify_all()

    def last_captured_step(self):
        """Newest step save() captured (durable or still on the
        writer); -1 when nothing was captured yet. Lets callers skip
        re-saving a boundary the cadence just snapshotted."""
        with self._cv:
            return self._last[1]["step"] if self._last is not None \
                else -1

    def flush(self, timeout=30.0):
        """Block until the async writer drained (fit exit, tests).
        Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending is not None or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    def _step_dir(self, g):
        return os.path.join(self.dir, f"step_{g}")

    @staticmethod
    def _rank_of(path):
        base = os.path.basename(path)
        try:
            return int(base[len("state_rank"):-len(".pd")])
        except ValueError:
            return -1

    def _snapshot_steps(self):
        out = []
        for p in _glob.glob(os.path.join(self.dir, "step_*")):
            base = os.path.basename(p)
            try:
                out.append(int(base[len("step_"):]))
            except ValueError:
                continue
        return sorted(out)

    def _write_snapshot(self, host, meta, lock_timeout=None):
        g = meta["step"]
        t0 = time.perf_counter()
        if lock_timeout is None:
            self._write_lock.acquire()
        elif not self._write_lock.acquire(timeout=lock_timeout):
            # the writer thread is wedged inside a write (hung
            # checkpoint FS) — an emergency save must NOT block
            # behind it: the watchdog calling us would deadlock and
            # never reach its checkpoint-then-ABORT kill
            raise TimeoutError(
                "checkpoint writer lock held past "
                f"{lock_timeout}s (wedged checkpoint FS?)")
        try:
            with _flight.in_flight("ckpt_write", f"step_{g}"):
                d = self._step_dir(g)
                # IO under _write_lock is this lock's PURPOSE (one
                # writer per snapshot dir); every other path into it
                # uses the bounded acquire(timeout=) above
                os.makedirs(d, exist_ok=True)  # noqa: PTA062
                payload = pickle.dumps(
                    {"schema": SCHEMA, "state": host}, protocol=4)
                # chaos site "ckpt_write": enospc/delay/stall enact
                # inside hit(); "torn" comes back for us to enact —
                # a PARTIAL rank file bypassing the atomic writer and
                # no manifest, exactly what a crash mid-write on a
                # non-atomic filesystem leaves (restore() must skip
                # it and fall back to the previous snapshot)
                if _chaos._armed:
                    act = _chaos.hit("ckpt_write", step=g)
                    if act is not None and act.fault == "torn":
                        with open(os.path.join(  # noqa: PTA062 — chaos-injected torn write, deliberately under the writer lock
                                d, f"state_rank{self.rank}.pd"),
                                "wb") as fh:
                            fh.write(payload[:max(1,
                                                  len(payload) // 2)])
                        raise OSError(
                            "chaos: torn checkpoint write (injected)")
                _atomic_write_bytes(
                    os.path.join(d, f"state_rank{self.rank}.pd"),
                    payload)
                if self.rank == 0:
                    # manifest LAST: its presence + complete flag is
                    # the published-snapshot marker (crash mid-write
                    # leaves a manifest-less dir restore skips)
                    _atomic_write_bytes(
                        os.path.join(d, "manifest.json"),
                        json.dumps(meta, indent=1).encode())
                    self._rotate()
        finally:
            self._write_lock.release()
        self._durable_step = max(self._durable_step, g)
        us = int((time.perf_counter() - t0) * 1e6)
        _cmon.stat_add("ckpt/saves", 1)
        _cmon.stat_add("ckpt/write_us", us)
        _cmon.stat_add("ckpt/bytes", len(payload))
        _flight.record("ckpt_save", step=g, bytes=len(payload), us=us)

    def _rotate(self):
        import shutil

        for g in self._snapshot_steps()[:-self.max_num]:
            shutil.rmtree(self._step_dir(g), ignore_errors=True)

    # -- restore ----------------------------------------------------------
    def restore(self):
        """Load the NEWEST VALID snapshot; returns the state tree (or
        None). Torn snapshots — truncated pickles, missing rank
        shards, corrupt manifests — fall back to the previous one.
        Sets `cursor` to {epoch, step_in_epoch, global_step} and fast-
        forwards `global_step`."""
        for g in reversed(self._snapshot_steps()):
            d = self._step_dir(g)
            try:
                with open(os.path.join(d, "manifest.json")) as f:
                    meta = json.load(f)
                if not meta.get("complete"):
                    continue
                files = sorted(_glob.glob(
                    os.path.join(d, "state_rank*.pd")))
                # only the ranks the manifest's world wrote: a step
                # dir REWRITTEN after a world shrink (emergency save
                # at the same boundary) may still hold the old
                # world's higher-rank shards, whose stale pieces
                # would merge over the fresh data
                ws = int(meta.get("world_size") or 0)
                if ws > 0:
                    files = [fp for fp in files
                             if self._rank_of(fp) < ws]
                    if len(files) != ws:
                        continue  # missing rank shard(s)
                if not files:
                    continue
                trees = []
                for fp in files:
                    with open(fp, "rb") as f:
                        trees.append(pickle.load(f)["state"])
                state = _merge_trees(trees)
                self.cursor = {
                    "epoch": int(meta["epoch"]),
                    "step_in_epoch": int(meta["step_in_epoch"]),
                    "global_step": int(meta["step"])}
                self.global_step = int(meta["step"])
                self._durable_step = int(meta["step"])
                _cmon.stat_add("ckpt/restores", 1)
                _flight.record("ckpt_restore", step=meta["step"])
                return state
            except _TORN:
                continue  # torn snapshot — previous one
        return None

    # -- emergency (watchdog / preemption) --------------------------------
    def set_state_provider(self, fn):
        """fn() -> (state, {"epoch","step_in_epoch","global_step"}) —
        refreshed by the fit callback at every step boundary so an
        emergency save captures the LAST COMPLETED step, not whatever
        half-donated buffers a hung dispatch holds."""
        self._state_provider = fn

    def emergency_save(self, reason="emergency", use_provider=True):
        """Best-effort SYNCHRONOUS step-boundary checkpoint: a fresh
        capture via the state provider when the arrays are readable,
        else the newest already-captured snapshot if it is not yet
        durable. Returns the step written, or None (which includes
        "the newest capture is already on disk" — success).
        use_provider=False skips the live capture: for callers that
        may run CONCURRENTLY with dispatches donating the captured
        buffers (e.g. a scale-event poll on another thread), only the
        already-hostified fallback is safe."""
        prov = self._state_provider if use_provider else None
        host = meta = None
        if prov is not None:
            try:
                state, cur = prov()
                specs = {}
                host = _hostify(state, specs)
                if _sanitize._donation:
                    host = _sanitize.verify_host_tree(
                        host, site="ckpt.emergency_save",
                        what="emergency snapshot")
                meta = {"schema": SCHEMA,
                        "step": int(cur.get("global_step", 0)),
                        "epoch": int(cur.get("epoch", 0)),
                        "step_in_epoch": int(cur.get("step_in_epoch",
                                                     0)),
                        "ts": round(time.time(), 3),
                        "world_size": self.world_size,
                        "mesh": _mesh_axes(), "arrays": specs,
                        "complete": True, "reason": reason}
            except Exception:
                host = None  # donated/deleted buffers mid-dispatch
        if host is None:
            with self._cv:
                last = self._last
            if last is None or last[1]["step"] <= self._durable_step:
                return None  # nothing newer than what's on disk
            host, meta = last
            meta = dict(meta, reason=reason)
        try:
            # bounded lock wait: if the async writer is wedged on a
            # hung FS, give up instead of deadlocking the caller
            # (possibly the watchdog thread itself)
            self._write_snapshot(host, meta,
                                 lock_timeout=self._lock_timeout_s)
        except Exception:
            _cmon.stat_add("ckpt/errors", 1)
            return None
        _cmon.stat_add("ckpt/emergency_saves", 1)
        _flight.record("ckpt_emergency", reason=reason,
                       step=meta["step"])
        return meta["step"]

    def _on_incident(self, reason):
        self.emergency_save(reason)

    # -- arming -----------------------------------------------------------
    def arm(self):
        """Watchdog checkpoint-then-abort + preemption: a hung
        collective (flight watchdog fire) or a SIGTERM now produces a
        resumable snapshot next to the flight bundle."""
        self._closing.clear()  # re-armed by a later fit
        # a preemption flag latched by a PREVIOUS fit must not make
        # this fit's saver stop at its first boundary (the handler is
        # only installed below, so no live signal can be lost here)
        self.preempted.clear()
        _flight.add_incident_hook(self._on_incident)
        self.install_preemption_handler()
        return self

    def install_preemption_handler(self, signum=None):
        """Chain a checkpoint-then-stop handler onto the preemption
        signal (PADDLE_CKPT_PREEMPT_SIGNAL, default SIGTERM; falsy
        disables). The handler sets `preempted` — Model.fit saves
        synchronously at the next step boundary and stops — and a
        background thread writes the flight "preempt" bundle + an
        emergency snapshot in case no boundary is ever reached.
        Main-thread only; returns True when installed."""
        if signum is None:
            name = os.environ.get("PADDLE_CKPT_PREEMPT_SIGNAL",
                                  "SIGTERM").strip()
            if name.lower() in ("", "0", "off", "none", "no"):
                return False
            signum = getattr(signal, name, None)
            if signum is None:
                try:
                    signum = int(name)
                except ValueError:
                    return False
        if threading.current_thread() is not threading.main_thread():
            return False
        if self._prev_sig is not None:
            return self._prev_sig[0] == signum
        try:
            prev = signal.signal(signum, self._on_preempt_signal)
        except (ValueError, OSError):
            return False
        self._prev_sig = (signum, prev)
        return True

    def uninstall_preemption_handler(self):
        if self._prev_sig is None:
            return
        signum, prev = self._prev_sig
        try:
            # NB: == not `is` — every access to self._on_preempt_signal
            # builds a fresh bound-method object, so `is` is always
            # False and the handler would never be restored (each fit
            # would chain another layer onto the last)
            if signal.getsignal(signum) == self._on_preempt_signal:
                signal.signal(signum, prev if prev is not None
                              else signal.SIG_DFL)
            # else: someone chained onto us — leave their chain alone
        except (ValueError, OSError):
            pass
        self._prev_sig = None

    def _on_preempt_signal(self, signum, frame):
        self.preempted.set()
        _flight.record("preempt", signal=int(signum))

        def _bg():
            # off the handler (it runs between bytecodes, possibly
            # over a held recorder/registry lock — flight's SIGUSR1
            # discipline)
            try:
                _flight.write_dump("preempt",
                                   extra={"signal": int(signum)})
            except Exception:
                pass
            # grace window: a LIVE fit loop checkpoints the next step
            # boundary synchronously itself (the saver callback sees
            # `preempted`). Capturing state HERE while dispatches are
            # still donating those buffers races XLA's frees at the
            # C++ level (observed: process SIGABRT mid-hostify), so
            # only fall back to an emergency capture once no save
            # lands — a wedged loop issues no dispatches, which makes
            # the capture safe (dead donated arrays raise cleanly and
            # emergency_save falls back to the last host snapshot).
            start = self._durable_step
            deadline = time.monotonic() + self._preempt_grace_s
            while time.monotonic() < deadline:
                if self._durable_step > start or self._closed \
                        or self._closing.is_set():
                    return  # boundary checkpoint landed / fit exited
                time.sleep(0.2)
            try:
                self.emergency_save("preempt")
            except Exception:
                pass

        self._preempt_thread = threading.Thread(
            target=_bg, name="paddle-ckpt-preempt", daemon=True)
        self._preempt_thread.start()
        prev = self._prev_sig[1] if self._prev_sig else None
        if callable(prev):
            prev(signum, frame)

    def close(self, timeout=30.0):
        """Disarm hooks and drain the writer (fit exit)."""
        self._closing.set()
        _flight.remove_incident_hook(self._on_incident)
        self.uninstall_preemption_handler()
        # the preemption bg thread does jax device->host work — let it
        # finish BEFORE the interpreter (and the XLA runtime) tears
        # down, or a daemon thread mid-hostify aborts the process
        # ("terminate called without an active exception") right after
        # the clean preempted stop it just enabled
        t, self._preempt_thread = self._preempt_thread, None
        if t is not None and t.is_alive():
            t.join(timeout)
        self._state_provider = None
        ok = self.flush(timeout)
        with self._cv:
            self._closed = True
            # the emergency fallback capture is a full host copy of
            # model + optimizer state; with the hooks disarmed nothing
            # can consume it — don't pin snapshot-sized RAM past the
            # fit
            self._last = None
            self._cv.notify_all()
        # JOIN the writer, don't just signal it: a daemon thread
        # still winding down while the interpreter finalizes races
        # the C++ runtime's static destructors (observed as
        # "terminate called without an active exception" SIGABRTs at
        # exit on the preemption path)
        w, self._writer = self._writer, None
        if w is not None and w.is_alive():
            w.join(timeout)
        return ok
