"""paddle.incubate.asp — automatic 2:4 structured sparsity.

Parity target: python/paddle/fluid/contrib/sparsity/asp.py
(prune_model, decorate/OptimizerWithSparsityGuarantee, set_excluded_
layers, calculate_density) + utils.py mask algorithms (mask_1d /
best-of-permutations n:m masks).

TPU-native notes: the reference exploits Ampere sparse tensor cores;
TPU MXUs have no 2:4 hardware path, so the capability here is the
TRAINING workflow — n:m masks computed along the REDUCTION (K) dim of
each GEMM (Linear [in, out] masks down columns; Conv masks the
flattened in*kh*kw dim per output channel — the reference reshapes
conv weights to 2D the same way), applied at prune time and re-applied
after every optimizer step (the sparsity-guarantee contract) — so
checkpoints carry hardware-valid 2:4 patterns."""
from __future__ import annotations

import warnings
import weakref

import numpy as np
import jax.numpy as jnp

__all__ = ["prune_model", "decorate", "calculate_density",
           "set_excluded_layers", "reset_excluded_layers",
           "create_mask", "check_mask_1d"]

_excluded = set()
# id(param) -> (weakref(param), mask). Weak so pruned models can be
# collected; decorate() snapshots only ITS optimizer's params.
_masks: dict = {}


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _mask_last_axis(flat2d, n, m):
    """[rows, K] -> n:m mask along K (keep the n largest |w| per
    m-group)."""
    groups = np.abs(flat2d).reshape(-1, m)
    drop = np.argsort(groups, axis=1)[:, : m - n]
    mask = np.ones_like(groups)
    np.put_along_axis(mask, drop, 0.0, axis=1)
    return mask.reshape(flat2d.shape)


def create_mask(w, n=2, m=4):
    """n:m mask along the GEMM reduction dim (reference
    sparsity/utils.py get_mask_1d + asp.py's conv reshape):
    - 2-D [in, out] (Linear, y = xW): groups run down axis 0, per
      output column;
    - 4-D [out, in, kh, kw] (Conv): flattened to [out, in*kh*kw],
      groups along the flattened reduction.
    Returns None when the reduction dim is not divisible by m."""
    w = np.asarray(w)
    if w.ndim == 2:
        if w.shape[0] % m:
            return None
        return _mask_last_axis(w.T, n, m).T.astype(w.dtype)
    if w.ndim == 4:
        out_c = w.shape[0]
        k = int(np.prod(w.shape[1:]))
        if k % m:
            return None
        return _mask_last_axis(w.reshape(out_c, k), n, m).reshape(
            w.shape).astype(w.dtype)
    if w.shape[-1] % m:
        return None
    return _mask_last_axis(w.reshape(-1, w.shape[-1]), n, m).reshape(
        w.shape).astype(w.dtype)


def check_mask_1d(mat, n=2, m=4):
    """True iff every m-group along the reduction dim has <= n
    nonzeros (same axis convention as create_mask)."""
    mat = np.asarray(mat)
    if mat.ndim == 2:
        view = mat.T
    elif mat.ndim == 4:
        view = mat.reshape(mat.shape[0], -1)
    else:
        view = mat.reshape(-1, mat.shape[-1])
    if view.shape[-1] % m:
        return False
    groups = (view.reshape(-1, m) != 0).sum(axis=1)
    return bool((groups <= n).all())


def calculate_density(tensor):
    arr = np.asarray(getattr(tensor, "_value", tensor))
    return float((arr != 0).sum() / arr.size)


def _prunable_params(model):
    from ...nn import Conv2D, Linear

    for layer in model.sublayers(include_self=True):
        if isinstance(layer, (Linear, Conv2D)):
            for name, p in layer.named_parameters(include_sublayers=False):
                if "weight" in name and p.name not in _excluded \
                        and len(p.shape) >= 2:
                    yield p


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute + apply n:m masks to every prunable weight (reference
    asp.py prune_model). Masks are remembered (weakly) so `decorate`d
    optimizers re-apply them after each step."""
    pruned = {}
    for p in _prunable_params(model):
        mask = create_mask(np.asarray(p._value), n=n, m=m)
        if mask is None:
            warnings.warn(
                f"asp: weight {p.name or id(p)} shape {tuple(p.shape)} "
                f"has a reduction dim not divisible by {m}; left dense")
            continue
        p._value = (jnp.asarray(p._value) * jnp.asarray(mask))
        if with_mask:
            _masks[id(p)] = (weakref.ref(p), mask)
        pruned[p.name or str(id(p))] = mask
    # purge entries whose params were collected
    for k in [k for k, (r, _) in _masks.items() if r() is None]:
        del _masks[k]
    return pruned


class OptimizerWithSparsityGuarantee:
    """reference asp.py:OptimizerWithSparsityGuarantee — masks are
    re-applied after every optimizer step so pruned weights stay 0
    through training. Only THIS optimizer's parameters are touched."""

    def __init__(self, optimizer):
        self._inner = optimizer
        # remember WHICH params are ours; consult the live _masks at
        # step time so the documented decorate-then-prune order works
        self._param_ids = {
            id(p) for p in
            (getattr(optimizer, "_parameter_list", None) or [])}

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _reapply(self):
        for pid, (ref, mask) in list(_masks.items()):
            if self._param_ids and pid not in self._param_ids:
                continue
            p = ref()
            if p is not None:
                p._value = jnp.asarray(p._value) * jnp.asarray(mask)

    def step(self):
        self._inner.step()
        self._reapply()

    def minimize(self, loss, *a, **kw):
        out = self._inner.minimize(loss, *a, **kw)
        self._reapply()
        return out


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
