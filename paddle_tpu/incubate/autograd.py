"""paddle.incubate.autograd — re-export of functional autodiff."""
from ..autograd.functional import jacobian, hessian, vjp, jvp
