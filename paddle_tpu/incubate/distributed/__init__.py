"""namespace (mirrors paddle.incubate.distributed)."""
from . import models
