"""Mixture-of-Experts with expert parallelism.

Parity target: the reference's MoE primitives
(`paddle/fluid/operators/collective/global_scatter_op.cc`,
`global_gather_op.cc`; Python `python/paddle/distributed/utils.py:57,179`)
— token routing across expert-parallel ranks. The reference snapshot
ships only the primitives; this module also provides the layer built on
them (the capability class the primitives exist for).

TPU-native design (GShard/Mesh-TF pattern, NOT a port of the CUDA ops):
instead of dynamic per-expert row counts (dynamic shapes — hostile to
XLA), routing uses a *static expert capacity*: each expert receives at
most C tokens per step. Dispatch and combine are einsums against a
[tokens, experts, capacity] one-hot tensor, so the whole MoE block is
three MXU matmuls plus elementwise — and when the expert dimension is
sharded over the 'ep' mesh axis, GSPMD lowers the dispatch/combine
einsums to `all_to_all` over ICI (exactly what global_scatter/
global_gather do with NCCL in the reference, derived by the compiler
instead of hand-written).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import weakref

from .....core.engine import apply_op, register_trace_exit_hook
from .....core.tensor import Parameter
from .....nn.layer.layers import Layer
from .....ops import random as _random
from .....distributed import mesh as mesh_mod

__all__ = ["MoELayer", "TopKGate", "moe_dispatch_combine"]

_live_moe_layers: "weakref.WeakSet" = weakref.WeakSet()


def _drop_trace_scoped_aux():
    # only clear leaked tracers — a concrete aux_loss from an eager
    # forward must survive unrelated compilations
    for layer in _live_moe_layers:
        aux = layer.aux_loss
        val = getattr(aux, "_value", aux)
        if isinstance(val, jax.core.Tracer):
            layer.aux_loss = None


register_trace_exit_hook(_drop_trace_scoped_aux)


def _constrain(x, spec):
    mesh = mesh_mod.get_mesh()
    if mesh is None:
        return x
    from .....jit.distributed import filter_spec

    fspec = filter_spec(P(*spec), mesh)
    if all(n is None for n in fspec):
        return x
    try:
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, fspec))
    except (ValueError, TypeError):
        return x


def _top2_gating(logits, capacity):
    """GShard top-2 gating. logits [N, E] f32 -> (combine [N,E,C],
    dispatch [N,E,C] bool, aux_loss scalar)."""
    n, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)
    # load-balancing auxiliary loss (GShard eq. (4)): mean gate prob x
    # mean assignment fraction, summed over experts, scaled by E
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux_loss = jnp.sum(density * density_proxy) * e

    gates2 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=gates.dtype)

    # position of each token inside its expert's buffer (0-based)
    pos1 = jnp.cumsum(mask1, axis=0) - mask1
    mask1 = mask1 * (pos1 < capacity)
    # second choices queue behind all first choices
    pos2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0)
    mask2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(gates * mask1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    p1 = jnp.sum(pos1 * mask1, axis=-1).astype(jnp.int32)
    p2 = jnp.sum(pos2 * mask2, axis=-1).astype(jnp.int32)
    oh1 = jax.nn.one_hot(p1, capacity, dtype=gates.dtype)
    oh2 = jax.nn.one_hot(p2, capacity, dtype=gates.dtype)
    combine = (g1[:, None, None] * mask1[:, :, None] * oh1[:, None, :]
               + g2[:, None, None] * mask2[:, :, None] * oh2[:, None, :])
    dispatch = combine > 0.0
    return combine, dispatch, aux_loss


def _top1_gating(logits, capacity):
    n, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux_loss = jnp.sum(density * density_proxy) * e
    pos1 = jnp.cumsum(mask1, axis=0) - mask1
    mask1 = mask1 * (pos1 < capacity)
    g1 = jnp.sum(gates * mask1, axis=-1)
    p1 = jnp.sum(pos1 * mask1, axis=-1).astype(jnp.int32)
    oh1 = jax.nn.one_hot(p1, capacity, dtype=gates.dtype)
    combine = g1[:, None, None] * mask1[:, :, None] * oh1[:, None, :]
    return combine, combine > 0.0, aux_loss


def moe_dispatch_combine(xt, combine, dispatch, expert_fn):
    """Dispatch tokens into [E, C, H] expert buffers, run expert_fn,
    combine back weighted by the gate. The two einsums are the
    global_scatter / global_gather analogs; with the expert dim sharded
    over 'ep', GSPMD emits all_to_all over ICI for them."""
    dtype = xt.dtype
    expert_in = jnp.einsum("nec,nh->ech", dispatch.astype(dtype), xt)
    expert_in = _constrain(expert_in, ("ep", None, None))
    expert_out = expert_fn(expert_in)
    expert_out = _constrain(expert_out, ("ep", None, None))
    return jnp.einsum("ech,nec->nh", expert_out,
                      combine.astype(expert_out.dtype))


def _k_moe_ffn(x, gate_w, w1, b1, w2, b2, top_k, capacity):
    """Full MoE FFN block: [B,S,H] -> ([B,S,H], aux_loss)."""
    b, s, h = x.shape
    xt = x.reshape(b * s, h)
    # gating math stays f32 even under bf16 training (GShard recipe)
    logits = xt.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    gate = _top2_gating if top_k == 2 else _top1_gating
    combine, dispatch, aux_loss = gate(logits, capacity)

    def expert_fn(ein):
        hmid = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", ein, w1)
                           + b1[:, None, :])
        return jnp.einsum("ecf,efh->ech", hmid, w2) + b2[:, None, :]

    y = moe_dispatch_combine(xt, combine, dispatch, expert_fn)
    return y.reshape(b, s, h).astype(x.dtype), aux_loss.astype(jnp.float32)


class TopKGate(Layer):
    """Top-k softmax gate (GShard). reference capability:
    distributed/utils.py routing counts; here the gate also produces the
    static-capacity dispatch/combine tensors."""

    def __init__(self, d_model, num_experts, top_k=2):
        super().__init__()
        if top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2 (GShard gating), "
                             f"got {top_k}")
        self.top_k = top_k
        self.num_experts = num_experts
        k = _random.next_key()
        w = (jax.random.normal(k, (d_model, num_experts), jnp.float32)
             * (1.0 / math.sqrt(d_model)))
        self.weight = Parameter(w, name="gate_w")
        self.add_parameter("weight", self.weight)


class MoELayer(Layer):
    """Expert-parallel FFN block.

    The E experts' weights are stacked with a leading expert dim carrying
    `dist_spec P('ep', ...)` — at rest each ep-rank holds E/ep experts.
    Forward = gate -> capacity dispatch (all_to_all under GSPMD) ->
    per-expert FFN (batched einsum on the MXU) -> combine (all_to_all).

    reference: global_scatter/global_gather capability class
    (`operators/collective/global_scatter_op.cc`) + the fused FFN
    (`incubate/nn/layer/fused_transformer.py` FusedFeedForward).

    After each forward, `self.aux_loss` holds the load-balancing loss
    tensor (differentiable) — add `aux_weight * layer.aux_loss` to the
    training loss *within the same forward/loss computation*. The
    attribute is reset to None when a compiled trace exits, so a tracer
    can never leak onto the long-lived layer (reading it outside the
    step yields a clear None rather than an escaped-tracer error).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, expert_axis="ep"):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.expert_axis = expert_axis
        self.gate = TopKGate(d_model, num_experts, top_k)

        ks = jax.random.split(_random.next_key(), 2)
        e, h, f = num_experts, d_model, d_hidden

        def normal(k, shape, scale):
            return scale * jax.random.normal(k, shape, dtype=jnp.float32)

        self.w1 = Parameter(normal(ks[0], (e, h, f), 1 / math.sqrt(h)),
                            name="moe_w1")
        self.b1 = Parameter(jnp.zeros((e, f), jnp.float32), name="moe_b1")
        self.w2 = Parameter(normal(ks[1], (e, f, h), 1 / math.sqrt(f)),
                            name="moe_w2")
        self.b2 = Parameter(jnp.zeros((e, h), jnp.float32), name="moe_b2")
        for name, p in (("w1", self.w1), ("b1", self.b1),
                        ("w2", self.w2), ("b2", self.b2)):
            p.dist_spec = P(*((expert_axis,) + (None,) * (p._value.ndim - 1)))
            self.add_parameter(name, p)
        self.aux_loss = None
        _live_moe_layers.add(self)

    def expert_capacity(self, num_tokens):
        return max(4, int(math.ceil(
            self.top_k * self.capacity_factor * num_tokens
            / self.num_experts)))

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        cap = self.expert_capacity(b * s)
        y, aux = apply_op("moe_ffn", _k_moe_ffn, x, self.gate.weight,
                          self.w1, self.b1, self.w2, self.b2,
                          top_k=self.top_k, capacity=cap)
        self.aux_loss = aux
        return y
