"""namespace (mirrors paddle.incubate.distributed.models)."""
from . import moe
