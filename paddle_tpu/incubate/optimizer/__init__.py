"""paddle.incubate.optimizer (reference:
python/paddle/incubate/optimizer/{lookahead,modelaverage}.py) — a real
subpackage so the reference's canonical import form works:
    from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage
"""
from ...optimizer.averaging import (  # noqa: F401
    ExponentialMovingAverage, LookAhead, ModelAverage)

__all__ = ["ExponentialMovingAverage", "LookAhead", "ModelAverage"]
