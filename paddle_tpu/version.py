"""Version info (reference: python/paddle/version.py, generated)."""
major = "0"
minor = "1"
patch = "0"
rc = "0"
full_version = f"{major}.{minor}.{patch}"
commit = "unknown"
istaged = False


def show():
    print(f"paddle_tpu {full_version}")
