// Package paddle — Go inference API over the C ABI.
//
// Parity target: paddle/fluid/inference/goapi/ (the reference wraps
// capi_exp with cgo exactly like this). The underlying C library
// (../capi/pd_inference_api.{h,cc}) is built and tested in-tree
// (tests/test_capi.py compiles and drives it); this package is the
// thin cgo shim the reference ships.
//
// Build (after building libpd_inference, see ../capi/__init__.py):
//
//	CGO_CFLAGS="-I/path/to/paddle_tpu/inference/capi" \
//	CGO_LDFLAGS="-L/path/to/build -lpd_inference" \
//	go build ./...
//
// The Go toolchain is not present in the framework CI image, so this
// file is validated structurally (tests/test_goapi.py checks every C
// symbol it references exists in the tested C header) rather than
// compiled there.
package paddle

/*
#include <stdint.h>
#include <stdlib.h>
#include "pd_inference_api.h"
*/
import "C"

import (
	"errors"
	"runtime"
	"unsafe"
)

// Init starts the embedded runtime (PD_Init). Call once per process.
func Init() error {
	if C.PD_Init() != 0 {
		return lastError()
	}
	return nil
}

// Finalize tears the runtime down (PD_Finalize).
func Finalize() { C.PD_Finalize() }

func lastError() error {
	return errors.New(C.GoString(C.PD_GetLastError()))
}

// Config mirrors the reference goapi Config.
type Config struct{ c *C.PD_Config }

func NewConfig() *Config {
	cfg := &Config{c: C.PD_ConfigCreate()}
	runtime.SetFinalizer(cfg, func(c *Config) {
		if c.c != nil {
			C.PD_ConfigDestroy(c.c)
		}
	})
	return cfg
}

// SetModel points at a jit.save / save_inference_model prefix.
func (cfg *Config) SetModel(prefix string) {
	cs := C.CString(prefix)
	defer C.free(unsafe.Pointer(cs))
	C.PD_ConfigSetModel(cfg.c, cs)
}

// SetOptimCacheDir sets the AOT executable cache directory.
func (cfg *Config) SetOptimCacheDir(dir string) {
	cs := C.CString(dir)
	defer C.free(unsafe.Pointer(cs))
	C.PD_ConfigSetOptimCacheDir(cfg.c, cs)
}

// Predictor mirrors the reference goapi Predictor.
type Predictor struct{ p *C.PD_Predictor }

func NewPredictor(cfg *Config) (*Predictor, error) {
	p := C.PD_PredictorCreate(cfg.c)
	if p == nil {
		return nil, lastError()
	}
	pred := &Predictor{p: p}
	runtime.SetFinalizer(pred, func(pr *Predictor) {
		if pr.p != nil {
			C.PD_PredictorDestroy(pr.p)
		}
	})
	return pred, nil
}

// GetInputNum returns the model's input arity.
func (pred *Predictor) GetInputNum() int {
	return int(C.PD_PredictorGetInputNum(pred.p))
}

// RunFloat feeds float32 inputs (data + shapes) and returns the first
// output tensor's data and shape (PD_PredictorRunFloat).
func (pred *Predictor) RunFloat(inputs [][]float32,
	shapes [][]int64) ([]float32, []int64, error) {
	n := len(inputs)
	if n == 0 || n != len(shapes) {
		return nil, nil, errors.New("inputs/shapes mismatch")
	}
	dataPtrs := make([]*C.float, n)
	shapePtrs := make([]*C.int64_t, n)
	ndims := make([]C.int, n)
	for i := range inputs {
		dataPtrs[i] = (*C.float)(unsafe.Pointer(&inputs[i][0]))
		shapePtrs[i] = (*C.int64_t)(unsafe.Pointer(&shapes[i][0]))
		ndims[i] = C.int(len(shapes[i]))
	}
	var outData *C.float
	var outShape *C.int64_t
	var outNdim C.int
	rc := C.PD_PredictorRunFloat(pred.p,
		(**C.float)(unsafe.Pointer(&dataPtrs[0])),
		(**C.int64_t)(unsafe.Pointer(&shapePtrs[0])),
		(*C.int)(unsafe.Pointer(&ndims[0])), C.int(n),
		&outData, &outShape, &outNdim)
	if rc != 0 {
		return nil, nil, lastError()
	}
	defer C.PD_Free(unsafe.Pointer(outData))
	defer C.PD_Free(unsafe.Pointer(outShape))
	nd := int(outNdim)
	shape := make([]int64, nd)
	total := int64(1)
	for i := 0; i < nd; i++ {
		shape[i] = int64(*(*C.int64_t)(unsafe.Pointer(
			uintptr(unsafe.Pointer(outShape)) +
				uintptr(i)*unsafe.Sizeof(C.int64_t(0)))))
		total *= shape[i]
	}
	out := make([]float32, total)
	src := unsafe.Slice((*float32)(unsafe.Pointer(outData)), total)
	copy(out, src)
	return out, shape, nil
}
