"""Continuous-batching scheduler (admit / evict / preempt between
fused decode dispatches).

The serving-architecture comparison (PAPERS.md arxiv 2605.25645) is
blunt about what makes TPU serving throughput: the decode program is
ONE fixed-shape compiled dispatch, and the scheduler's whole job is
keeping its batch slots full — requests join and leave BETWEEN
dispatches, never inside one. This module is that control loop's
policy half (the engine owns the dispatches):

  * FIFO admission: `add()` queues, `schedule()` admits while a batch
    slot AND the KV pool's admission check (`can_admit`: prompt
    blocks — less any prefix-cached blocks — plus a decode lookahead
    sized for the engine's speculative width k, since one verify
    dispatch can land up to k tokens) both say yes. Admission goes
    through `cache.admit()`, which maps cached prefix blocks
    copy-on-write and charges only the uncached remainder. Admission
    is a chaos site (`serve_admit`) — slow clients and
    admission-time faults inject there.
  * Block growth: a running request crossing a block boundary asks
    `ensure_capacity()` for its next block before the dispatch that
    writes into it.
  * Preemption: when the pool can't grow a running request (or the
    dispatch OOMs — the engine routes RESOURCE_EXHAUSTED here), the
    YOUNGEST running request is evicted: its blocks free immediately,
    its prompt + generated-so-far re-queues at the FRONT, and a later
    admission re-prefills it — generated tokens are kept, so the
    replayed decode continues exactly where it stopped (the vLLM
    recompute policy; sampling seeds are position-keyed so replay is
    deterministic).
  * `static_batching=True` degrades admission to the classic
    serve-a-batch-drain-a-batch policy — the bench twin that measures
    what continuous batching buys.

Overload/SLO policy (ISSUE 13 — the robustness ring production TPU
serving is won on, per the arxiv 2605.25645 comparison):

  * DEADLINES — `SamplingParams(deadline_s=)` (default
    `PADDLE_SERVE_DEADLINE_S`, 0 = none) stamps the request with an
    absolute expiry at arrival. Every admission pass first sweeps the
    waiting queue for expired entries and retires them to the
    `EXPIRED` terminal state (`serve/deadline_aborts`) — a request
    that waited past its SLO must not burn prefill + decode HBM on an
    answer nobody is waiting for. RUNNING requests are never
    deadline-killed mid-decode: they already paid prefill, finishing
    them is the cheaper path.
  * LOAD SHEDDING — `max_queue` (default `PADDLE_SERVE_MAX_QUEUE`,
    0 = unbounded) bounds the waiting queue; `add()` on a full queue
    raises `EngineOverloaded` (`serve/shed`) instead of queueing
    unboundedly. Expired entries are swept before the bound is
    judged, so a queue full of corpses can't shed live traffic.
    Eviction requeues bypass the bound: an evicted request already
    holds an admission promise.
  * PRIORITY-AWARE EVICTION — victims are picked lowest-`priority`
    first, then latest-deadline (most slack loses the least), then
    youngest-admitted (the PR-10 vLLM policy as the final tiebreak).

Every state change feeds the PR-1 monitor hub: `serve/requests`,
`serve/evictions`, `serve/queue_depth` (gauge), `serve/shed`,
`serve/deadline_aborts`, and the engine adds tokens/latency counters
around the dispatches.
"""
from __future__ import annotations

import itertools
import math
import time
from collections import deque

from ...core import monitor as _cmon
from ...monitor import chaos as _chaos
from ...monitor import flight as _flight
from ...monitor import trace as _trace

__all__ = ["SamplingParams", "Request", "Scheduler",
           "EngineOverloaded", "env_max_queue", "env_deadline_s",
           "WAITING", "RUNNING", "FINISHED", "ABORTED", "EXPIRED",
           "EXPORTED"]

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"
ABORTED = "aborted"
EXPIRED = "expired"      # deadline passed while WAITING (ISSUE 13)
EXPORTED = "exported"    # handed off for replay on another engine

_TERMINAL = (FINISHED, ABORTED, EXPIRED, EXPORTED)


def env_max_queue():
    """PADDLE_SERVE_MAX_QUEUE — waiting-queue bound before `add()`
    sheds with EngineOverloaded (default 0 = unbounded)."""
    return max(0, _flight._env_int("PADDLE_SERVE_MAX_QUEUE", 0))


def env_deadline_s():
    """PADDLE_SERVE_DEADLINE_S — default per-request deadline in
    seconds (default 0 = no deadline)."""
    return max(0.0, _flight._env_float("PADDLE_SERVE_DEADLINE_S",
                                       0.0))


class EngineOverloaded(RuntimeError):
    """Load shedding: the waiting queue is at `max_queue` (or the
    engine is draining) — the caller should back off and retry, or
    route to another replica. Carries the shedding engine's state
    summary in `.engine_state` when the engine raised it."""

    def __init__(self, msg, engine_state=None):
        super().__init__(msg)
        self.engine_state = engine_state or {}


def _int_like(v):
    """True for ints and integer numpy scalars; False for bools,
    floats, strings — the types the compiled sampler would either
    silently coerce or crash on mid-dispatch."""
    if isinstance(v, bool):
        return False
    if isinstance(v, int):
        return True
    # numpy integer scalars without importing numpy here
    return (hasattr(v, "dtype")
            and getattr(v.dtype, "kind", "") in ("i", "u")
            and getattr(v, "ndim", 1) == 0)


class SamplingParams:
    """Per-request generation controls (the vLLM surface, trimmed to
    what the compiled sampler implements) plus the ISSUE-13 SLO
    fields: `deadline_s` (wall-clock budget from arrival; expired
    WAITING requests retire as EXPIRED at admission) and `priority`
    (higher survives eviction longer).

    Every field is validated HERE, at intake — a negative `top_k`
    would otherwise flow uncaught into the compiled double-argsort
    sampler and mask every logit, and a float `seed` would crash the
    uint32 cast inside a dispatch instead of at the API edge."""

    def __init__(self, max_new_tokens=16, temperature=0.0, top_k=0,
                 eos_token_id=None, stop_token_ids=(), seed=0,
                 deadline_s=None, priority=0):
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if not _int_like(top_k):
            raise ValueError(
                f"top_k must be an int, got {type(top_k).__name__} "
                f"({top_k!r})")
        if top_k < 0:
            raise ValueError(
                f"top_k must be >= 0 (0 = no filtering), got "
                f"{top_k} — a negative k would mask every logit in "
                "the compiled rank-filter sampler")
        if not _int_like(seed):
            raise ValueError(
                f"seed must be an int, got {type(seed).__name__} "
                f"({seed!r})")
        if eos_token_id is not None and not _int_like(eos_token_id):
            raise ValueError(
                f"eos_token_id must be an int or None, got "
                f"{type(eos_token_id).__name__} ({eos_token_id!r})")
        stop_token_ids = tuple(stop_token_ids)
        for t in stop_token_ids:
            if not _int_like(t):
                raise ValueError(
                    f"stop_token_ids must be ints, got "
                    f"{type(t).__name__} ({t!r})")
        if deadline_s is None:
            deadline_s = env_deadline_s() or None
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 (None = no deadline), got "
                f"{deadline_s}")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.stop_token_ids = tuple(int(t) for t in stop_token_ids)
        self.seed = int(seed)
        self.deadline_s = (None if deadline_s is None
                           else float(deadline_s))
        self.priority = int(priority)

    def __repr__(self):
        return (f"SamplingParams(max_new_tokens="
                f"{self.max_new_tokens}, temperature="
                f"{self.temperature}, top_k={self.top_k}, "
                f"priority={self.priority})")


class Request:
    """One generation request moving through the engine."""

    _ids = itertools.count()

    def __init__(self, prompt_ids, sampling=None, on_token=None,
                 req_id=None, trace_id=None):
        self.req_id = (f"req-{next(Request._ids)}"
                       if req_id is None else str(req_id))
        self.prompt_ids = [int(t) for t in prompt_ids]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.sampling = sampling or SamplingParams()
        self.on_token = on_token
        self.state = WAITING
        self.output_ids = []
        self.slot = None           # decode batch slot while RUNNING
        self.evictions = 0
        # tokens covered by shared prefix blocks at LAST admission —
        # the engine's prefill skips them (tail-only prefill)
        self.cached_tokens = 0
        # speculative-decode realign flag: True after a round accepts
        # every proposal (one draft-KV position is then stale; the
        # next round's realign step rewrites it)
        self._spec_gap = False
        self.token_times = []      # perf_counter per emitted token
        self.arrival = time.monotonic()
        # TTFT/e2e latency anchor on the SAME clock as token_times
        # (perf_counter); `arrival` stays the monotonic deadline/
        # queue-wait clock — mixing the two would skew every gap
        self.arrival_perf = time.perf_counter()
        # absolute expiry (monotonic); None = no SLO. Survives
        # eviction/export so a replayed request keeps its budget.
        self.deadline = (self.arrival + self.sampling.deadline_s
                         if self.sampling.deadline_s else None)
        # -- per-request trace (ISSUE 15): trace_id minted at intake
        # (add_request/submit construct the Request there) and kept
        # through eviction/export/import-replay; `trace` is the
        # bounded stage timeline monitor.trace.note() appends to
        self.trace = []
        self.trace_dropped = 0
        self.trace_id = (trace_id if trace_id is not None
                         else (_trace.mint() if _trace._armed
                               else None))
        if _trace._armed:
            _trace.note(self, "add", prompt=len(self.prompt_ids))
        self._queue_waited = False  # first-admission wait observed

    @property
    def priority(self):
        return self.sampling.priority

    def expired(self, now=None):
        return (self.deadline is not None
                and (time.monotonic() if now is None else now)
                > self.deadline)

    @property
    def context_len(self):
        """Tokens whose K/V must be live for the next decode."""
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def finished(self):
        return self.state in _TERMINAL

    def stop_hit(self, token):
        s = self.sampling
        return (token == s.eos_token_id
                or token in s.stop_token_ids)

    def __repr__(self):
        return (f"<Request {self.req_id} {self.state} "
                f"prompt={len(self.prompt_ids)} "
                f"out={len(self.output_ids)}>")


class Scheduler:
    """Admission/eviction policy over one PagedKVCache + a fixed
    decode batch width."""

    def __init__(self, cache, max_batch, max_seq_len,
                 static_batching=False, max_queue=None,
                 spec_tokens=1):
        self.cache = cache
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.static_batching = bool(static_batching)
        # speculative width: one verify dispatch can append up to
        # `spec_tokens` tokens, so admission's decode lookahead and
        # ensure_capacity's growth target must both cover k — or the
        # verify dispatch right after admission evicts what was just
        # admitted
        self.spec_tokens = max(1, int(spec_tokens))
        self._lookahead = max(
            1, math.ceil(self.spec_tokens / cache.block_size))
        self.max_queue = (env_max_queue() if max_queue is None
                          else max(0, int(max_queue)))
        self.draining = False      # drain(): stop admitting
        self.waiting = deque()
        self.running = {}          # slot -> Request
        self._admit_seq = itertools.count()
        self._admitted_at = {}     # req_id -> admission ordinal

    # -- queue -------------------------------------------------------
    def add(self, request, force=False):
        """Queue a request. `force=True` bypasses the drain gate and
        the shed bound — failover re-admission only: an exported
        request already holds an admission promise from the replica
        that lost it, and dropping it to a full queue would break the
        router's every-request-completes contract."""
        if request.context_len >= self.max_seq_len:
            raise ValueError(
                f"{request.req_id}: prompt ({request.context_len}) "
                f"leaves no room under max_seq_len="
                f"{self.max_seq_len}")
        if force:
            request.state = WAITING
            self.waiting.append(request)
            self._sync_depth()
            return request
        if self.draining:
            _cmon.stat_add("serve/shed", 1)
            _flight.record("serve_shed", req=request.req_id,
                           reason="draining")
            raise EngineOverloaded(
                f"{request.req_id}: engine is draining — retry on "
                "another replica or after resume()")
        if self.max_queue and len(self.waiting) >= self.max_queue:
            # sweep corpses first: a queue full of already-expired
            # entries must not shed live traffic
            self.expire_waiting()
            if len(self.waiting) >= self.max_queue:
                _cmon.stat_add("serve/shed", 1)
                _flight.record("serve_shed", req=request.req_id,
                               reason="queue_full",
                               depth=len(self.waiting))
                raise EngineOverloaded(
                    f"{request.req_id}: waiting queue full "
                    f"({len(self.waiting)} >= max_queue="
                    f"{self.max_queue}) — load shed")
        request.state = WAITING
        self.waiting.append(request)
        self._sync_depth()
        return request

    def _requeue_front(self, request):
        request.state = WAITING
        request.slot = None
        self.waiting.appendleft(request)
        self._sync_depth()

    def _sync_depth(self):
        _cmon.stat_set("serve/queue_depth", len(self.waiting))

    def has_work(self):
        return bool(self.waiting or self.running)

    # -- admission ---------------------------------------------------
    def _free_slots(self):
        return [s for s in range(self.max_batch)
                if s not in self.running]

    def expire_waiting(self, now=None):
        """Retire WAITING requests whose deadline passed (EXPIRED
        terminal state, `serve/deadline_aborts`). Runs at the head of
        every admission pass AND before the shed bound is judged —
        admission is the last point a dead-on-arrival request can be
        dropped for free (no pool blocks, no prefill). Returns the
        expired requests."""
        now = time.monotonic() if now is None else now
        expired = [r for r in self.waiting if r.expired(now)]
        for req in expired:
            self.waiting.remove(req)
            self.finish(req, state=EXPIRED)
            _cmon.stat_add("serve/deadline_aborts", 1)
        if expired:
            self._sync_depth()
        return expired

    def schedule(self, on_admit=None):
        """Admit as many waiting requests as slots + pool allow.
        `on_admit(req)` runs IMMEDIATELY after each admission (the
        engine prefills there) so a fault later in the same pass —
        an admission-site chaos raise for request N+1 — can never
        strand request N admitted-but-never-prefilled; the chaos hit
        itself fires BEFORE the request takes any pool resources.
        Expired waiting requests retire first; a draining scheduler
        admits nothing (running requests still finish). Static-
        batching mode only admits into an EMPTY batch."""
        admitted = []
        self.expire_waiting()
        if self.draining:
            return admitted
        if self.static_batching and self.running:
            return admitted
        slots = self._free_slots()
        while slots and self.waiting:
            req = self.waiting[0]
            need_tokens = req.context_len
            ctx_ids = req.prompt_ids + req.output_ids
            cached_blocks, _ = self.cache.probe_prefix(ctx_ids)
            if not self.cache.can_admit(
                    need_tokens, lookahead_blocks=self._lookahead,
                    cached_blocks=cached_blocks):
                break
            if _chaos._armed:
                # slow-client / admission faults land here, BEFORE
                # the request takes any pool resources
                _chaos.hit("serve_admit", req=req.req_id)
            self.waiting.popleft()
            nblocks = self.cache.blocks_for_tokens(need_tokens)
            cached = self.cache.admit(req.req_id, ctx_ids)
            if cached is None:     # raced the lookahead margin
                self._requeue_front(req)
                break
            req.cached_tokens = cached
            req.state = RUNNING
            req.slot = slots.pop(0)
            self.running[req.slot] = req
            self._admitted_at[req.req_id] = next(self._admit_seq)
            admitted.append(req)
            if not req._queue_waited:
                # queue-wait distribution (ISSUE 15): arrival ->
                # FIRST admission only — an eviction's re-admission
                # wait is recompute churn, not intake queueing
                req._queue_waited = True
                _cmon.hist_observe(
                    "serve/hist/queue_wait_us",
                    (time.monotonic() - req.arrival) * 1e6)
            _flight.record("serve_admit", req=req.req_id,
                           slot=req.slot, blocks=nblocks)
            if _trace._armed:
                _trace.note(req, "admit", slot=req.slot,
                            blocks=nblocks, readmit=req.evictions)
            if on_admit is not None:
                on_admit(req)
        self._sync_depth()
        return admitted

    # -- block growth / preemption -----------------------------------
    def ensure_capacity(self, request, new_tokens=None):
        """Grow the request's table to cover its next `new_tokens`
        tokens (default: the scheduler's speculative width — a
        verify dispatch may land up to k at once); evicts other
        requests under pool pressure. False when the request itself
        had to be evicted (pool too small even after evicting
        everyone younger) — or was ALREADY evicted by an earlier
        grow in the same pass (growing a non-running request would
        allocate blocks no dispatch ever uses: the PTA070 leak the
        serving sanitizer hunts)."""
        if self.running.get(request.slot) is not request:
            return False
        if new_tokens is None:
            new_tokens = self.spec_tokens
        need = self.cache.blocks_for_tokens(
            request.context_len + new_tokens)
        while len(self.cache.allocator.owned(request.req_id)) < need:
            got = self.cache.allocator.alloc(request.req_id, 1)
            if got is not None:
                continue
            victim = self._pick_victim(exclude=request)
            if victim is None:
                self.evict(request)
                return False
            self.evict(victim)
        return True

    def _pick_victim(self, exclude=None):
        """Eviction victim, worst SLO position first: lowest
        `priority`, then latest deadline (no deadline = infinitely
        late — the most slack loses the least by recomputing), then
        youngest-admitted (the PR-10 vLLM recompute policy as the
        final tiebreak)."""
        cands = [r for r in self.running.values() if r is not exclude]
        if not cands:
            return None
        return max(cands, key=lambda r: (
            -r.priority,
            r.deadline if r.deadline is not None else math.inf,
            self._admitted_at.get(r.req_id, -1)))

    def evict(self, request):
        """Preempt a running request: free its blocks NOW, requeue it
        at the front with its generated tokens kept (re-prefill will
        rebuild the KV it lost)."""
        self.running.pop(request.slot, None)
        self.cache.allocator.release(request.req_id)
        self._admitted_at.pop(request.req_id, None)
        request.cached_tokens = 0   # re-admission re-probes
        request._spec_gap = False   # re-prefill rewrites draft KV
        request.evictions += 1
        self._requeue_front(request)
        _cmon.stat_add("serve/evictions", 1)
        _flight.record("serve_evict", req=request.req_id,
                       evictions=request.evictions)
        if _trace._armed:
            _trace.note(request, "evict",
                        evictions=request.evictions,
                        kept_tokens=len(request.output_ids))

    # -- completion --------------------------------------------------
    def finish(self, request, state=FINISHED):
        """Terminal transition from ANY state: releases blocks, and
        removes a still-queued entry so no terminal path
        (finish/abort/expire/export) can leave a corpse in the
        waiting deque with `serve/queue_depth` overcounting — the
        router failover hot path aborts WAITING requests. The deque
        scan is gated on the WAITING state (only add/_requeue_front
        put requests there), so the common RUNNING-completion path
        stays O(1) under a deep backlog."""
        was_waiting = request.state == WAITING
        request.state = state
        if request.slot is not None:
            self.running.pop(request.slot, None)
            request.slot = None
        if was_waiting and request in self.waiting:
            self.waiting.remove(request)
            self._sync_depth()
        self.cache.allocator.release(request.req_id)
        self._admitted_at.pop(request.req_id, None)
        if state == FINISHED:
            # e2e request latency (ISSUE 15): arrival at THIS engine
            # -> completion, on the token_times clock. A failover
            # replay re-anchors at import (each engine leg is its own
            # observation; the trace timeline carries the whole story)
            _cmon.hist_observe(
                "serve/hist/e2e_us",
                (time.perf_counter() - request.arrival_perf) * 1e6)
        _flight.record("serve_finish", req=request.req_id,
                       tokens=len(request.output_ids), state=state)
        if _trace._armed:
            _trace.note(request, state,
                        tokens=len(request.output_ids))

    def abort(self, request):
        """Cancel wherever it is; blocks release immediately and a
        queued entry leaves the waiting deque (+ depth gauge) in the
        same call."""
        self.finish(request, state=ABORTED)
