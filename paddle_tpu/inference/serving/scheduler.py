"""Continuous-batching scheduler (admit / evict / preempt between
fused decode dispatches).

The serving-architecture comparison (PAPERS.md arxiv 2605.25645) is
blunt about what makes TPU serving throughput: the decode program is
ONE fixed-shape compiled dispatch, and the scheduler's whole job is
keeping its batch slots full — requests join and leave BETWEEN
dispatches, never inside one. This module is that control loop's
policy half (the engine owns the dispatches):

  * FIFO admission: `add()` queues, `schedule()` admits while a batch
    slot AND the KV pool's admission check (`can_admit`: prompt
    blocks + one decode-lookahead block) both say yes. Admission is a
    chaos site (`serve_admit`) — slow clients and admission-time
    faults inject there.
  * Block growth: a running request crossing a block boundary asks
    `ensure_capacity()` for its next block before the dispatch that
    writes into it.
  * Preemption: when the pool can't grow a running request (or the
    dispatch OOMs — the engine routes RESOURCE_EXHAUSTED here), the
    YOUNGEST running request is evicted: its blocks free immediately,
    its prompt + generated-so-far re-queues at the FRONT, and a later
    admission re-prefills it — generated tokens are kept, so the
    replayed decode continues exactly where it stopped (the vLLM
    recompute policy; sampling seeds are position-keyed so replay is
    deterministic).
  * `static_batching=True` degrades admission to the classic
    serve-a-batch-drain-a-batch policy — the bench twin that measures
    what continuous batching buys.

Every state change feeds the PR-1 monitor hub: `serve/requests`,
`serve/evictions`, `serve/queue_depth` (gauge), and the engine adds
tokens/latency counters around the dispatches.
"""
from __future__ import annotations

import itertools
from collections import deque

from ...core import monitor as _cmon
from ...monitor import chaos as _chaos
from ...monitor import flight as _flight

__all__ = ["SamplingParams", "Request", "Scheduler",
           "WAITING", "RUNNING", "FINISHED", "ABORTED"]

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"
ABORTED = "aborted"


class SamplingParams:
    """Per-request generation controls (the vLLM surface, trimmed to
    what the compiled sampler implements)."""

    def __init__(self, max_new_tokens=16, temperature=0.0, top_k=0,
                 eos_token_id=None, stop_token_ids=(), seed=0):
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_token_id = eos_token_id
        self.stop_token_ids = tuple(stop_token_ids)
        self.seed = int(seed)

    def __repr__(self):
        return (f"SamplingParams(max_new_tokens="
                f"{self.max_new_tokens}, temperature="
                f"{self.temperature}, top_k={self.top_k})")


class Request:
    """One generation request moving through the engine."""

    _ids = itertools.count()

    def __init__(self, prompt_ids, sampling=None, on_token=None,
                 req_id=None):
        self.req_id = (f"req-{next(Request._ids)}"
                       if req_id is None else str(req_id))
        self.prompt_ids = [int(t) for t in prompt_ids]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.sampling = sampling or SamplingParams()
        self.on_token = on_token
        self.state = WAITING
        self.output_ids = []
        self.slot = None           # decode batch slot while RUNNING
        self.evictions = 0
        self.token_times = []      # perf_counter per emitted token

    @property
    def context_len(self):
        """Tokens whose K/V must be live for the next decode."""
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def finished(self):
        return self.state in (FINISHED, ABORTED)

    def stop_hit(self, token):
        s = self.sampling
        return (token == s.eos_token_id
                or token in s.stop_token_ids)

    def __repr__(self):
        return (f"<Request {self.req_id} {self.state} "
                f"prompt={len(self.prompt_ids)} "
                f"out={len(self.output_ids)}>")


class Scheduler:
    """Admission/eviction policy over one PagedKVCache + a fixed
    decode batch width."""

    def __init__(self, cache, max_batch, max_seq_len,
                 static_batching=False):
        self.cache = cache
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.static_batching = bool(static_batching)
        self.waiting = deque()
        self.running = {}          # slot -> Request
        self._admit_seq = itertools.count()
        self._admitted_at = {}     # req_id -> admission ordinal

    # -- queue -------------------------------------------------------
    def add(self, request):
        if request.context_len >= self.max_seq_len:
            raise ValueError(
                f"{request.req_id}: prompt ({request.context_len}) "
                f"leaves no room under max_seq_len="
                f"{self.max_seq_len}")
        request.state = WAITING
        self.waiting.append(request)
        self._sync_depth()
        return request

    def _requeue_front(self, request):
        request.state = WAITING
        request.slot = None
        self.waiting.appendleft(request)
        self._sync_depth()

    def _sync_depth(self):
        _cmon.stat_set("serve/queue_depth", len(self.waiting))

    def has_work(self):
        return bool(self.waiting or self.running)

    # -- admission ---------------------------------------------------
    def _free_slots(self):
        return [s for s in range(self.max_batch)
                if s not in self.running]

    def schedule(self, on_admit=None):
        """Admit as many waiting requests as slots + pool allow.
        `on_admit(req)` runs IMMEDIATELY after each admission (the
        engine prefills there) so a fault later in the same pass —
        an admission-site chaos raise for request N+1 — can never
        strand request N admitted-but-never-prefilled; the chaos hit
        itself fires BEFORE the request takes any pool resources.
        Static-batching mode only admits into an EMPTY batch."""
        admitted = []
        if self.static_batching and self.running:
            return admitted
        slots = self._free_slots()
        while slots and self.waiting:
            req = self.waiting[0]
            need_tokens = req.context_len
            if not self.cache.can_admit(need_tokens):
                break
            if _chaos._armed:
                # slow-client / admission faults land here, BEFORE
                # the request takes any pool resources
                _chaos.hit("serve_admit", req=req.req_id)
            self.waiting.popleft()
            nblocks = self.cache.blocks_for_tokens(need_tokens)
            got = self.cache.allocator.alloc(req.req_id, nblocks)
            if got is None:        # raced the lookahead margin
                self._requeue_front(req)
                break
            req.state = RUNNING
            req.slot = slots.pop(0)
            self.running[req.slot] = req
            self._admitted_at[req.req_id] = next(self._admit_seq)
            admitted.append(req)
            _flight.record("serve_admit", req=req.req_id,
                           slot=req.slot, blocks=nblocks)
            if on_admit is not None:
                on_admit(req)
        self._sync_depth()
        return admitted

    # -- block growth / preemption -----------------------------------
    def ensure_capacity(self, request):
        """Grow the request's table to cover its next token; evicts
        other requests under pool pressure. False when the request
        itself had to be evicted (pool too small even after evicting
        everyone younger) — or was ALREADY evicted by an earlier
        grow in the same pass (growing a non-running request would
        allocate blocks no dispatch ever uses: the PTA070 leak the
        serving sanitizer hunts)."""
        if self.running.get(request.slot) is not request:
            return False
        need = self.cache.blocks_for_tokens(request.context_len + 1)
        while len(self.cache.allocator.owned(request.req_id)) < need:
            got = self.cache.allocator.alloc(request.req_id, 1)
            if got is not None:
                continue
            victim = self._pick_victim(exclude=request)
            if victim is None:
                self.evict(request)
                return False
            self.evict(victim)
        return True

    def _pick_victim(self, exclude=None):
        """Youngest-admitted running request (vLLM policy: the newest
        request loses the least recompute work)."""
        cands = [r for r in self.running.values() if r is not exclude]
        if not cands:
            return None
        return max(cands,
                   key=lambda r: self._admitted_at.get(r.req_id, -1))

    def evict(self, request):
        """Preempt a running request: free its blocks NOW, requeue it
        at the front with its generated tokens kept (re-prefill will
        rebuild the KV it lost)."""
        self.running.pop(request.slot, None)
        self.cache.allocator.release(request.req_id)
        self._admitted_at.pop(request.req_id, None)
        request.evictions += 1
        self._requeue_front(request)
        _cmon.stat_add("serve/evictions", 1)
        _flight.record("serve_evict", req=request.req_id,
                       evictions=request.evictions)

    # -- completion --------------------------------------------------
    def finish(self, request, state=FINISHED):
        request.state = state
        if request.slot is not None:
            self.running.pop(request.slot, None)
            request.slot = None
        self.cache.allocator.release(request.req_id)
        self._admitted_at.pop(request.req_id, None)
        _flight.record("serve_finish", req=request.req_id,
                       tokens=len(request.output_ids), state=state)

    def abort(self, request):
        """Cancel wherever it is; blocks release immediately."""
        if request in self.waiting:
            self.waiting.remove(request)
            self._sync_depth()
        self.finish(request, state=ABORTED)
