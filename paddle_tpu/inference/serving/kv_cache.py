"""Block-allocated paged KV cache (the vLLM/Ragged-Paged-Attention
memory model, PAPERS.md arxiv 2604.15464, on TPU-native pools).

Generation workloads can't preallocate per-request [max_seq] KV
tensors — at 8+ concurrent mixed-length requests that wastes most of
HBM on padding. Instead the cache is a FIXED device pool of
fixed-size blocks per layer:

    k/v pools:  [num_layers, num_blocks, block_size, n_head, head_dim]

and every request owns a host-side BLOCK TABLE — the ordered list of
pool block ids covering its tokens. Token `t` of a request lives at
`(table[t // block_size], t % block_size)`. Attention reads K/V
through the table (dense gather fallback, or the Pallas ragged
paged-attention kernel in `incubate.nn.pallas.paged_attention`), so
sequences of wildly different lengths share one pool with ZERO
padding waste beyond the last partial block.

Block 0 is the reserved NULL block: padded prompt positions and
inactive batch slots write their garbage K/V there, so the compiled
programs never need a "don't write" branch — reads never see it
because every read is masked by the request's context length.

The allocator is the admission-control truth: `can_admit()` answers
whether a prompt fits, `alloc()`/`release()` move blocks between the
free list and per-owner tables, and the `serve/kv_blocks/{used,free}`
gauges (PR-1 monitor hub) track occupancy. Pool sizing comes from
`PADDLE_SERVE_POOL_BYTES` or — on devices with PJRT stats — from the
PR-5 `monitor.memory.memory_stats()` free-HBM reading, discounted by
the per-program footprints already resident.

PTA07x (block-leak) accounting: with `PADDLE_SANITIZE=serving` armed,
double-free / free-of-unowned trips a PTA071 finding at the faulting
call, and `audit_leaks(live_owners)` reports PTA070 for blocks still
owned by requests the serving layer no longer tracks. The static half
lives in `paddle_tpu.analysis.serving`.
"""
from __future__ import annotations

import math
import os
from collections import deque

import numpy as np

from ...core import monitor as _cmon
from ...monitor import sanitize as _san

__all__ = ["BlockAllocator", "PagedKVCache", "NULL_BLOCK",
           "env_block_size", "env_pool_bytes", "env_max_batch",
           "auto_num_blocks", "bytes_per_block"]

NULL_BLOCK = 0  # reserved garbage-dump block, never owned

_DEF_BLOCK_SIZE = 16
_DEF_MAX_BATCH = 8
# CPU / no-stats fallback pool budget — big enough for the tests'
# tiny models, small enough to exercise eviction in the chaos flood
_DEF_POOL_BYTES = 64 << 20


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_block_size():
    """PADDLE_SERVE_BLOCK_SIZE — tokens per KV block (default 16)."""
    return max(1, _env_int("PADDLE_SERVE_BLOCK_SIZE", _DEF_BLOCK_SIZE))


def env_pool_bytes():
    """PADDLE_SERVE_POOL_BYTES — total KV pool budget in bytes
    (default 0 = auto-size from device memory stats)."""
    return _env_int("PADDLE_SERVE_POOL_BYTES", 0)


def env_max_batch():
    """PADDLE_SERVE_MAX_BATCH — decode batch width (default 8)."""
    return max(1, _env_int("PADDLE_SERVE_MAX_BATCH", _DEF_MAX_BATCH))


def bytes_per_block(num_layers, block_size, n_head, head_dim, dtype):
    """HBM cost of ONE block id across all layers, K and V."""
    itemsize = np.dtype(dtype).itemsize
    return 2 * num_layers * block_size * n_head * head_dim * itemsize


def auto_num_blocks(per_block, pool_bytes=None, fraction=0.45):
    """Pool size in blocks: the explicit budget when given (env or
    argument), else `fraction` of the device's free HBM per the PR-5
    memory stats (bytes_limit - bytes_in_use already accounts for the
    resident compiled programs + params), else the CPU fallback."""
    budget = pool_bytes if pool_bytes else env_pool_bytes()
    if not budget:
        try:
            from ...monitor import memory as _memory

            stats = _memory.memory_stats() or {}
            limit = int(stats.get("bytes_limit", 0) or 0)
            used = int(stats.get("bytes_in_use", 0) or 0)
            if limit > used > 0:
                budget = int((limit - used) * fraction)
        except Exception:
            budget = 0
    if not budget:
        budget = _DEF_POOL_BYTES
    # +1: block 0 is the null block, not usable capacity
    return max(2, budget // max(1, per_block) + 1)


class BlockAllocator:
    """Host-side free-list over the pool's block ids.

    Block 0 (NULL_BLOCK) is never handed out. Ownership is tracked
    per request id so leaks are attributable: `release(owner)` frees
    everything an owner holds, `audit_leaks(live)` reports blocks
    owned by ids the caller no longer tracks (PTA070)."""

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 null + 1 usable), got "
                f"{num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free = deque(range(1, self.num_blocks))
        self._owned = {}  # owner id -> [block ids]
        self._sync_gauges()

    # -- occupancy ---------------------------------------------------
    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def used_blocks(self):
        return self.num_blocks - 1 - len(self._free)

    def owners(self):
        return sorted(self._owned)

    def owned(self, owner):
        return list(self._owned.get(owner, ()))

    def can_alloc(self, n):
        return len(self._free) >= n

    def _sync_gauges(self):
        _cmon.stat_set("serve/kv_blocks/used", self.used_blocks)
        _cmon.stat_set("serve/kv_blocks/free", self.free_blocks)

    # -- alloc/free --------------------------------------------------
    def alloc(self, owner, n=1):
        """Give `owner` `n` more blocks; returns the new block ids, or
        None when the pool can't satisfy the request (the caller's cue
        to evict — never a partial grant)."""
        if n <= 0:
            return []
        if len(self._free) < n:
            return None
        got = [self._free.popleft() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(got)
        self._sync_gauges()
        return got

    def release(self, owner):
        """Free every block `owner` holds; returns how many. Unknown
        owners are a no-op (a request evicted before its first alloc
        has nothing to free)."""
        blocks = self._owned.pop(owner, None)
        if not blocks:
            return 0
        self._free.extend(blocks)
        self._sync_gauges()
        return len(blocks)

    def free_one(self, owner, block_id):
        """Return one specific block (shrink paths). Freeing a block
        the owner doesn't hold is the double-free bug class — PTA071
        when the serving sanitizer is armed, ValueError always."""
        blocks = self._owned.get(owner)
        if not blocks or block_id not in blocks:
            if getattr(_san, "_serving", False):
                _san._emit(
                    "PTA071",
                    f"free of block {block_id} not owned by "
                    f"{owner!r} (double-free or foreign free)",
                    dedup=("PTA071", owner, block_id))
            raise ValueError(
                f"block {block_id} is not owned by {owner!r}")
        blocks.remove(block_id)
        if not blocks:
            self._owned.pop(owner, None)
        self._free.append(block_id)
        self._sync_gauges()
        return block_id

    # -- leak audit (PTA070 runtime half) ----------------------------
    def audit_leaks(self, live_owners=()):
        """Blocks owned by request ids the serving layer no longer
        tracks are leaked — every completed/evicted/aborted request
        must have released. Returns {owner: [blocks]} of leaks; with
        the `serving` sanitize family armed each leak also emits a
        PTA070 finding through the PR-9 machinery."""
        live = set(live_owners)
        leaked = {o: list(b) for o, b in self._owned.items()
                  if o not in live and b}
        if leaked and getattr(_san, "_serving", False):
            for owner, blocks in sorted(leaked.items(),
                                        key=lambda kv: str(kv[0])):
                _san._emit(
                    "PTA070",
                    f"KV block leak: {len(blocks)} block(s) still "
                    f"owned by finished/unknown request {owner!r}",
                    dedup=("PTA070", owner))
        return leaked


class PagedKVCache:
    """The device pools + the allocator + per-request block tables."""

    def __init__(self, num_layers, num_heads, head_dim,
                 block_size=None, num_blocks=None, pool_bytes=None,
                 dtype=None):
        import jax.numpy as jnp

        self.block_size = int(block_size or env_block_size())
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype or jnp.float32)
        per_block = bytes_per_block(num_layers, self.block_size,
                                    num_heads, head_dim, self.dtype)
        if num_blocks is None:
            num_blocks = auto_num_blocks(per_block,
                                         pool_bytes=pool_bytes)
        self.num_blocks = int(num_blocks)
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self.allocator = BlockAllocator(self.num_blocks)

    # -- geometry ----------------------------------------------------
    def blocks_for_tokens(self, n_tokens):
        return max(1, math.ceil(n_tokens / self.block_size))

    def can_admit(self, n_tokens, lookahead_blocks=1):
        """Admission control: room for the prompt's blocks plus a
        decode lookahead so a request admitted now can generate at
        least one block of tokens before pool pressure."""
        need = self.blocks_for_tokens(n_tokens) + lookahead_blocks
        return self.allocator.can_alloc(need)

    def block_table(self, owner, max_blocks):
        """Padded int32 device-table row for one request: its owned
        blocks in token order, NULL_BLOCK beyond."""
        blocks = self.allocator.owned(owner)
        if len(blocks) > max_blocks:
            raise ValueError(
                f"request {owner!r} holds {len(blocks)} blocks > "
                f"max_blocks_per_seq={max_blocks}")
        row = np.full((max_blocks,), NULL_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        return row

    def reset_pools(self):
        """Fresh zero pools — recovery after a failed DONATING
        dispatch consumed the old ones (a real RESOURCE_EXHAUSTED
        mid-execution deletes donated buffers). The caller must
        re-prefill every sequence: allocator state survives but the
        K/V contents are gone."""
        import jax.numpy as jnp

        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    # -- defrag ------------------------------------------------------
    def defrag(self):
        """Compact allocated blocks to the front of the pool (one
        device gather per pool) so a long-lived server's free list
        stays contiguous — contiguous tables DMA better through the
        paged kernel's block streaming. Returns the number of blocks
        that moved; owner tables are rewritten in place."""
        owners = self.allocator.owners()
        mapping = {NULL_BLOCK: NULL_BLOCK}
        nxt = 1
        for owner in owners:
            for b in self.allocator._owned[owner]:
                mapping[b] = nxt
                nxt += 1
        moved = sum(1 for old, new in mapping.items() if old != new)
        if not moved:
            return 0
        # perm[new] = old; untouched tail keeps identity so freed
        # block contents (never read — reads are context-masked) need
        # no care beyond staying in range
        perm = np.arange(self.num_blocks)
        for old, new in mapping.items():
            perm[new] = old
        import jax.numpy as jnp

        idx = jnp.asarray(perm)
        self.k = self.k[:, idx]
        self.v = self.v[:, idx]
        for owner in owners:
            self.allocator._owned[owner] = [
                mapping[b] for b in self.allocator._owned[owner]]
        self.allocator._free = deque(
            range(nxt, self.num_blocks))
        self.allocator._sync_gauges()
        return moved
