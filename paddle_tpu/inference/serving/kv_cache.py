"""Block-allocated paged KV cache (the vLLM/Ragged-Paged-Attention
memory model, PAPERS.md arxiv 2604.15464, on TPU-native pools).

Generation workloads can't preallocate per-request [max_seq] KV
tensors — at 8+ concurrent mixed-length requests that wastes most of
HBM on padding. Instead the cache is a FIXED device pool of
fixed-size blocks per layer:

    k/v pools:  [num_layers, num_blocks, block_size, n_head, head_dim]

and every request owns a host-side BLOCK TABLE — the ordered list of
pool block ids covering its tokens. Token `t` of a request lives at
`(table[t // block_size], t % block_size)`. Attention reads K/V
through the table (dense gather fallback, or the Pallas ragged
paged-attention kernel in `incubate.nn.pallas.paged_attention`), so
sequences of wildly different lengths share one pool with ZERO
padding waste beyond the last partial block.

Block 0 is the reserved NULL block: padded prompt positions and
inactive batch slots write their garbage K/V there, so the compiled
programs never need a "don't write" branch — reads never see it
because every read is masked by the request's context length.

The allocator is the admission-control truth: `can_admit()` answers
whether a prompt fits, `alloc()`/`release()` move blocks between the
free list and per-owner tables, and the `serve/kv_blocks/{used,free}`
gauges (PR-1 monitor hub) track occupancy. Pool sizing comes from
`PADDLE_SERVE_POOL_BYTES` or — on devices with PJRT stats — from the
PR-5 `monitor.memory.memory_stats()` free-HBM reading, discounted by
the per-program footprints already resident.

Prefix caching (copy-on-write sharing): every block carries a
REFCOUNT, and FULL immutable blocks are published in a content-hash
index keyed by a chain hash (each block's digest folds in its
predecessor's, so a hit at depth i proves the whole prefix matches
AND that repeated identical chunks inside one prompt never collide).
`PagedKVCache.admit()` maps a new request's cached prefix blocks
into its table by bumping refcounts — no data movement — and
allocates only the uncached remainder; prefill then runs only the
tail. Shared blocks are immutable: the engine's write positions are
always >= the cached prefix, and `check_cow()` enforces it. A block
returns to the free list only when its LAST reference drops, which
also deregisters its hash (so eviction of one sharer never reclaims
— or republishes stale — shared content).

PTA07x (block-leak) accounting: with `PADDLE_SANITIZE=serving` armed,
double-free / free-of-unowned trips a PTA071 finding at the faulting
call, `audit_leaks(live_owners)` reports PTA070 for blocks still
owned by requests the serving layer no longer tracks, and PTA074
flags copy-on-write violations (a shared block written through, or a
block physically reclaimed while another table still maps it). The
static half lives in `paddle_tpu.analysis.serving`.
"""
from __future__ import annotations

import hashlib
import math
import os
from collections import deque

import numpy as np

from ...core import monitor as _cmon
from ...monitor import sanitize as _san

__all__ = ["BlockAllocator", "PagedKVCache", "NULL_BLOCK",
           "env_block_size", "env_pool_bytes", "env_max_batch",
           "env_spec_k", "env_spec_draft", "env_prefix_cache",
           "auto_num_blocks", "bytes_per_block", "prefix_hashes"]

NULL_BLOCK = 0  # reserved garbage-dump block, never owned

_DEF_BLOCK_SIZE = 16
_DEF_MAX_BATCH = 8
# CPU / no-stats fallback pool budget — big enough for the tests'
# tiny models, small enough to exercise eviction in the chaos flood
_DEF_POOL_BYTES = 64 << 20


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_block_size():
    """PADDLE_SERVE_BLOCK_SIZE — tokens per KV block (default 16)."""
    return max(1, _env_int("PADDLE_SERVE_BLOCK_SIZE", _DEF_BLOCK_SIZE))


def env_pool_bytes():
    """PADDLE_SERVE_POOL_BYTES — total KV pool budget in bytes
    (default 0 = auto-size from device memory stats)."""
    return _env_int("PADDLE_SERVE_POOL_BYTES", 0)


def env_max_batch():
    """PADDLE_SERVE_MAX_BATCH — decode batch width (default 8)."""
    return max(1, _env_int("PADDLE_SERVE_MAX_BATCH", _DEF_MAX_BATCH))


def env_spec_k():
    """PADDLE_SERVE_SPEC_K — speculative tokens per dispatch
    (default 1 = speculation off, plain one-token decode)."""
    return max(1, min(8, _env_int("PADDLE_SERVE_SPEC_K", 1)))


def env_spec_draft():
    """PADDLE_SERVE_SPEC_DRAFT — draft model layer count (default
    0 = auto: half the target's layers, minimum 1)."""
    return max(0, _env_int("PADDLE_SERVE_SPEC_DRAFT", 0))


def env_prefix_cache():
    """PADDLE_SERVE_PREFIX_CACHE — 1 enables copy-on-write prefix
    block sharing (default 0 = off)."""
    return 1 if _env_int("PADDLE_SERVE_PREFIX_CACHE", 0) else 0


def prefix_hashes(tokens, block_size, n_blocks=None):
    """Chain hashes for the leading FULL blocks of a token sequence:
    digest(i) = sha256(digest(i-1) || tokens of block i). The chain
    makes a depth-i hit prove the entire prefix matches and keeps
    repeated identical chunks within one prompt distinct."""
    if n_blocks is None:
        n_blocks = len(tokens) // block_size
    out = []
    h = b"\x00" * 32
    for i in range(n_blocks):
        m = hashlib.sha256()
        m.update(h)
        m.update(np.asarray(tokens[i * block_size:(i + 1) * block_size],
                            np.int64).tobytes())
        h = m.digest()
        out.append(h)
    return out


def bytes_per_block(num_layers, block_size, n_head, head_dim, dtype):
    """HBM cost of ONE block id across all layers, K and V."""
    itemsize = np.dtype(dtype).itemsize
    return 2 * num_layers * block_size * n_head * head_dim * itemsize


def auto_num_blocks(per_block, pool_bytes=None, fraction=0.45):
    """Pool size in blocks: the explicit budget when given (env or
    argument), else `fraction` of the device's free HBM per the PR-5
    memory stats (bytes_limit - bytes_in_use already accounts for the
    resident compiled programs + params), else the CPU fallback."""
    budget = pool_bytes if pool_bytes else env_pool_bytes()
    if not budget:
        try:
            from ...monitor import memory as _memory

            stats = _memory.memory_stats() or {}
            limit = int(stats.get("bytes_limit", 0) or 0)
            used = int(stats.get("bytes_in_use", 0) or 0)
            if limit > used > 0:
                budget = int((limit - used) * fraction)
        except Exception:
            budget = 0
    if not budget:
        budget = _DEF_POOL_BYTES
    # +1: block 0 is the null block, not usable capacity
    return max(2, budget // max(1, per_block) + 1)


class BlockAllocator:
    """Host-side free-list over the pool's block ids.

    Block 0 (NULL_BLOCK) is never handed out. Ownership is tracked
    per request id so leaks are attributable: `release(owner)` drops
    every reference an owner holds, `audit_leaks(live)` reports
    blocks owned by ids the caller no longer tracks (PTA070).

    Refcounts: a freshly allocated block has refcount 1; `share()`
    maps it into another owner's table copy-on-write (refcount up,
    no data movement). A block is physically reclaimed — returned to
    the free list and dropped from the content-hash index — only
    when its LAST reference goes, so evicting one sharer can never
    free (or stale-publish) blocks another request still reads."""

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 null + 1 usable), got "
                f"{num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free = deque(range(1, self.num_blocks))
        self._owned = {}  # owner id -> [block ids]
        self._refcnt = {}  # block id -> live references
        self._by_hash = {}  # chain digest -> block id
        self._hash_of = {}  # block id -> chain digest
        self._sync_gauges()

    # -- occupancy ---------------------------------------------------
    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def used_blocks(self):
        return self.num_blocks - 1 - len(self._free)

    def owners(self):
        return sorted(self._owned)

    def owned(self, owner):
        return list(self._owned.get(owner, ()))

    def can_alloc(self, n):
        return len(self._free) >= n

    def refcount(self, block_id):
        return self._refcnt.get(block_id, 0)

    def _sync_gauges(self):
        _cmon.stat_set("serve/kv_blocks/used", self.used_blocks)
        _cmon.stat_set("serve/kv_blocks/free", self.free_blocks)

    # -- alloc/free --------------------------------------------------
    def alloc(self, owner, n=1):
        """Give `owner` `n` more blocks; returns the new block ids, or
        None when the pool can't satisfy the request (the caller's cue
        to evict — never a partial grant)."""
        if n <= 0:
            return []
        if len(self._free) < n:
            return None
        got = [self._free.popleft() for _ in range(n)]
        for b in got:
            self._refcnt[b] = 1
        self._owned.setdefault(owner, []).extend(got)
        self._sync_gauges()
        return got

    def share(self, owner, block_id):
        """Map a LIVE block into `owner`'s table copy-on-write: the
        refcount goes up and nothing moves. The callers' contract is
        that shared blocks are full and immutable — `check_cow`
        enforces it on write paths."""
        if block_id == NULL_BLOCK or block_id not in self._refcnt:
            raise ValueError(
                f"cannot share unallocated block {block_id}")
        self._refcnt[block_id] += 1
        self._owned.setdefault(owner, []).append(block_id)
        self._sync_gauges()
        return block_id

    def _deref(self, block_id):
        """Drop one reference; physically reclaim on the last one.
        Returns 1 when the block actually hit the free list."""
        rc = self._refcnt.get(block_id, 1) - 1
        if rc > 0:
            self._refcnt[block_id] = rc
            return 0
        self._refcnt.pop(block_id, None)
        digest = self._hash_of.pop(block_id, None)
        if digest is not None and self._by_hash.get(digest) == block_id:
            del self._by_hash[digest]
        if getattr(_san, "_serving", False):
            # defensive PTA074 half: reclaiming a block some OTHER
            # table still maps means a refcount was lost somewhere
            holders = [o for o, bl in self._owned.items()
                       if block_id in bl]
            if holders:
                _san._emit(
                    "PTA074",
                    f"block {block_id} physically reclaimed while "
                    f"still mapped by {holders!r} (refcount lost)",
                    dedup=("PTA074", "reclaim", block_id))
        self._free.append(block_id)
        return 1

    def check_cow(self, block_id):
        """Copy-on-write guard: a block mapped by more than one
        request is immutable — writing through it would corrupt a
        stranger's context. PTA074 when the serving sanitizer is
        armed, ValueError always."""
        rc = self._refcnt.get(block_id, 1)
        if rc > 1:
            if getattr(_san, "_serving", False):
                _san._emit(
                    "PTA074",
                    f"write to shared block {block_id} (refcount "
                    f"{rc}) without copy-on-write",
                    dedup=("PTA074", "cow", block_id))
            raise ValueError(
                f"block {block_id} is shared by {rc} requests and "
                f"immutable (copy-on-write required)")
        return block_id

    # -- content-hash index (prefix cache) ---------------------------
    def register_hash(self, block_id, digest):
        """Publish one full immutable block under its chain digest.
        Lookup-first: an already-published digest (or an already-
        published block) keeps its existing mapping. Returns 1 on a
        new registration, 0 on skip."""
        if digest in self._by_hash or block_id in self._hash_of:
            return 0
        if block_id == NULL_BLOCK or block_id not in self._refcnt:
            raise ValueError(
                f"cannot index unallocated block {block_id}")
        self._by_hash[digest] = block_id
        self._hash_of[block_id] = digest
        return 1

    def lookup_hash(self, digest):
        return self._by_hash.get(digest)

    def clear_hash_index(self):
        """Forget every published block — pool resets zero the K/V
        contents, so pre-reset digests would serve garbage."""
        self._by_hash.clear()
        self._hash_of.clear()

    def release(self, owner):
        """Drop every reference `owner` holds; returns how many
        references were dropped (shared blocks stay resident for
        their other owners). Unknown owners are a no-op (a request
        evicted before its first alloc has nothing to free)."""
        blocks = self._owned.pop(owner, None)
        if not blocks:
            return 0
        for b in blocks:
            self._deref(b)
        self._sync_gauges()
        return len(blocks)

    def free_one(self, owner, block_id):
        """Return one specific block (shrink paths). Freeing a block
        the owner doesn't hold is the double-free bug class — PTA071
        when the serving sanitizer is armed, ValueError always."""
        blocks = self._owned.get(owner)
        if not blocks or block_id not in blocks:
            if getattr(_san, "_serving", False):
                _san._emit(
                    "PTA071",
                    f"free of block {block_id} not owned by "
                    f"{owner!r} (double-free or foreign free)",
                    dedup=("PTA071", owner, block_id))
            raise ValueError(
                f"block {block_id} is not owned by {owner!r}")
        blocks.remove(block_id)
        if not blocks:
            self._owned.pop(owner, None)
        self._deref(block_id)
        self._sync_gauges()
        return block_id

    # -- leak audit (PTA070 runtime half) ----------------------------
    def audit_leaks(self, live_owners=()):
        """Blocks owned by request ids the serving layer no longer
        tracks are leaked — every completed/evicted/aborted request
        must have released. Returns {owner: [blocks]} of leaks; with
        the `serving` sanitize family armed each leak also emits a
        PTA070 finding through the PR-9 machinery."""
        live = set(live_owners)
        leaked = {o: list(b) for o, b in self._owned.items()
                  if o not in live and b}
        if leaked and getattr(_san, "_serving", False):
            for owner, blocks in sorted(leaked.items(),
                                        key=lambda kv: str(kv[0])):
                _san._emit(
                    "PTA070",
                    f"KV block leak: {len(blocks)} block(s) still "
                    f"owned by finished/unknown request {owner!r}",
                    dedup=("PTA070", owner))
        return leaked


class PagedKVCache:
    """The device pools + the allocator + per-request block tables."""

    def __init__(self, num_layers, num_heads, head_dim,
                 block_size=None, num_blocks=None, pool_bytes=None,
                 dtype=None, draft_layers=0, prefix_cache=False):
        import jax.numpy as jnp

        self.block_size = int(block_size or env_block_size())
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype or jnp.float32)
        self.draft_layers = int(draft_layers)
        self.prefix_cache = bool(prefix_cache)
        per_block = bytes_per_block(num_layers, self.block_size,
                                    num_heads, head_dim, self.dtype)
        if num_blocks is None:
            num_blocks = auto_num_blocks(per_block,
                                         pool_bytes=pool_bytes)
        self.num_blocks = int(num_blocks)
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        # draft-model twin pools address through the SAME allocator
        # and tables — the chain-hash identity that lets two requests
        # share target KV holds for draft KV too, so one refcount
        # covers both
        self.k_draft = self.v_draft = None
        if self.draft_layers:
            dshape = (self.draft_layers,) + shape[1:]
            self.k_draft = jnp.zeros(dshape, self.dtype)
            self.v_draft = jnp.zeros(dshape, self.dtype)
        self.allocator = BlockAllocator(self.num_blocks)

    # -- geometry ----------------------------------------------------
    def blocks_for_tokens(self, n_tokens):
        return max(1, math.ceil(n_tokens / self.block_size))

    def can_admit(self, n_tokens, lookahead_blocks=1,
                  cached_blocks=0):
        """Admission control: room for the prompt's blocks (less any
        already cached) plus a decode lookahead so a request admitted
        now can generate at least one block of tokens before pool
        pressure. Speculative decoding passes a k-aware lookahead —
        a verify dispatch can land up to k tokens at once."""
        need = max(0, self.blocks_for_tokens(n_tokens)
                   - cached_blocks) + lookahead_blocks
        return self.allocator.can_alloc(need)

    # -- prefix cache ------------------------------------------------
    def probe_prefix(self, tokens):
        """(cached_blocks, block_ids): the longest chain of leading
        FULL blocks already published, capped BELOW the full context
        so the tail prefill always has >= 1 real token to run (and a
        row to sample from)."""
        if not self.prefix_cache or not len(tokens):
            return 0, []
        cap = max(0, (len(tokens) - 1) // self.block_size)
        ids = []
        for digest in prefix_hashes(tokens, self.block_size, cap):
            b = self.allocator.lookup_hash(digest)
            if b is None:
                break
            ids.append(b)
        return len(ids), ids

    def admit(self, owner, tokens):
        """Atomically give `owner` the blocks for its context: cached
        prefix blocks map copy-on-write (shared ids lead the table,
        matching their token positions), only the remainder comes off
        the free list. Returns the cached TOKEN count (0 when the
        cache is off or cold), or None when the pool can't cover the
        uncached remainder — never a partial grant."""
        total = self.blocks_for_tokens(len(tokens))
        n_shared, shared = self.probe_prefix(tokens)
        fresh = total - n_shared
        if not self.allocator.can_alloc(fresh):
            return None
        for b in shared:
            self.allocator.share(owner, b)
        if fresh and self.allocator.alloc(owner, fresh) is None:
            for b in shared:  # can't happen single-threaded; unwind
                self.allocator.free_one(owner, b)
            return None
        if n_shared:
            _cmon.stat_add("serve/prefix/hits", 1)
            _cmon.stat_add("serve/prefix/blocks_shared", n_shared)
        return n_shared * self.block_size

    def register_prefix(self, owner, tokens):
        """Publish `owner`'s full prompt blocks (written, immutable
        from here on) in the content index so later admissions can
        share them. Lookup-first — blocks already published, and
        digests already claimed, keep their existing mapping. Decode
        extends context into NEW blocks only, so published content
        never mutates. Returns how many blocks were newly published."""
        if not self.prefix_cache:
            return 0
        blocks = self.allocator.owned(owner)
        full = min(len(tokens) // self.block_size, len(blocks))
        n = 0
        for i, digest in enumerate(
                prefix_hashes(tokens, self.block_size, full)):
            n += self.allocator.register_hash(blocks[i], digest)
        return n

    def block_table(self, owner, max_blocks):
        """Padded int32 device-table row for one request: its owned
        blocks in token order, NULL_BLOCK beyond."""
        blocks = self.allocator.owned(owner)
        if len(blocks) > max_blocks:
            raise ValueError(
                f"request {owner!r} holds {len(blocks)} blocks > "
                f"max_blocks_per_seq={max_blocks}")
        row = np.full((max_blocks,), NULL_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        return row

    def reset_pools(self):
        """Fresh zero pools — recovery after a failed DONATING
        dispatch consumed the old ones (a real RESOURCE_EXHAUSTED
        mid-execution deletes donated buffers). The caller must
        re-prefill every sequence: allocator state survives but the
        K/V contents are gone."""
        import jax.numpy as jnp

        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        if self.draft_layers:
            dshape = (self.draft_layers,) + shape[1:]
            self.k_draft = jnp.zeros(dshape, self.dtype)
            self.v_draft = jnp.zeros(dshape, self.dtype)
        # zeroed pools invalidate every published prefix — serving a
        # pre-reset digest would share garbage KV
        self.allocator.clear_hash_index()

    # -- defrag ------------------------------------------------------
    def defrag(self):
        """Compact allocated blocks to the front of the pool (one
        device gather per pool) so a long-lived server's free list
        stays contiguous — contiguous tables DMA better through the
        paged kernel's block streaming. Returns the number of blocks
        that moved; owner tables are rewritten in place."""
        owners = self.allocator.owners()
        mapping = {NULL_BLOCK: NULL_BLOCK}
        nxt = 1
        for owner in owners:
            for b in self.allocator._owned[owner]:
                if b not in mapping:  # shared blocks move ONCE
                    mapping[b] = nxt
                    nxt += 1
        moved = sum(1 for old, new in mapping.items() if old != new)
        if not moved:
            return 0
        # perm[new] = old; untouched tail keeps identity so freed
        # block contents (never read — reads are context-masked) need
        # no care beyond staying in range
        perm = np.arange(self.num_blocks)
        for old, new in mapping.items():
            perm[new] = old
        import jax.numpy as jnp

        idx = jnp.asarray(perm)
        self.k = self.k[:, idx]
        self.v = self.v[:, idx]
        if self.k_draft is not None:
            self.k_draft = self.k_draft[:, idx]
            self.v_draft = self.v_draft[:, idx]
        for owner in owners:
            self.allocator._owned[owner] = [
                mapping[b] for b in self.allocator._owned[owner]]
        self.allocator._refcnt = {
            mapping[b]: c
            for b, c in self.allocator._refcnt.items()}
        self.allocator._by_hash = {
            h: mapping[b]
            for h, b in self.allocator._by_hash.items()}
        self.allocator._hash_of = {
            mapping[b]: h
            for b, h in self.allocator._hash_of.items()}
        self.allocator._free = deque(
            range(nxt, self.num_blocks))
        self.allocator._sync_gauges()
        return moved
