"""GPT-2 paged-serving forward passes (prefill + single-token decode).

The serving engine never calls `GPTModel.forward` — re-running the
full prompt for every generated token is O(S^2) per request. Instead
this module owns the two compiled programs of the generation path:

  * `prefill_step` — ONE causal forward over the (block-padded)
    prompt that also scatters every position's K/V into the paged
    pools through the request's block table, and samples the first
    generated token from the last REAL prompt row.
  * `decode_step`  — one token per running sequence: embed, scan the
    layer stack reading/writing K/V through the pools, ragged paged
    attention over each request's cached context, sample.

Both reuse the training model's own math helpers (`_layer_norm`,
`_residual_layer_norm`, `_attention` from `text.models.gpt`) so the
serving path computes EXACTLY what the training forward computes —
the e2e contract is greedy tokens identical to a sequential
full-re-forward loop, and every numerical divergence between the two
paths is a bug, not noise.

Sampling is in-program and per-request: `temperature == 0` is exact
argmax (greedy), `temperature > 0` draws from the (optionally
top-k-filtered) softmax with a seed the HOST derives from (request
seed, absolute token index) — so an evicted-and-re-prefilled request
replays the same random choices it would have made uninterrupted,
whatever batch it lands in.

Functions take the raw jnp parameter tree (`extract_params`), not
Layers: the engine jits them with donated pools, and the PR-8
persistent compile cache keys their StableHLO like any other program.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...text.models.gpt import (_attention, _layer_norm,
                                _residual_layer_norm)

__all__ = ["extract_params", "prefill_step", "decode_step",
           "sample_tokens", "seed_for"]


def extract_params(model):
    """(jnp param tree, GPTConfig) from GPTForCausalLM / GPTModel."""
    gpt = getattr(model, "gpt", model)
    tree = gpt._params_tree()
    params = jax.tree_util.tree_map(
        lambda p: p._value if hasattr(p, "_value") else jnp.asarray(p),
        tree)
    return params, gpt.config


def seed_for(request_seed, token_index):
    """Host-side per-token sampling seed: a pure function of the
    request's seed and the ABSOLUTE position being sampled, so
    replayed decodes (eviction -> re-prefill) and different batch
    compositions draw identical randomness."""
    return (int(request_seed) * 1000003 + int(token_index)) \
        & 0x7FFFFFFF


def sample_tokens(logits, temperature, top_k, seeds):
    """Per-request next-token selection over [B, V] logits.

    temperature[b] == 0 -> exact argmax (greedy decode);
    temperature[b] > 0  -> categorical over logits/temperature with
    ranks >= top_k[b] masked out when top_k[b] > 0. The rank trick
    (double argsort) keeps k per-request and traced — `lax.top_k`
    would force one compiled program per distinct k."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def draw(lg, t, k, seed):
        ranks = jnp.argsort(jnp.argsort(-lg))
        keep = ranks < jnp.where(k > 0, k, vocab)
        lg = jnp.where(keep, lg, -jnp.inf)
        key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
        return jax.random.categorical(
            key, lg / jnp.maximum(t, 1e-6)).astype(jnp.int32)

    sampled = jax.vmap(draw)(logits, temperature, top_k, seeds)
    return jnp.where(temperature > 0, sampled, greedy)


def _scatter_positions(block_table, positions, block_size):
    """(pool block ids, in-block offsets) for a vector of token
    positions resolved through ONE request's block table."""
    return (jnp.take(block_table, positions // block_size, axis=0),
            positions % block_size)


def prefill_step(params, ids, prompt_len, k_pool, v_pool, block_table,
                 temperature, top_k, seed, *, n_head, eps, block_size):
    """Causal forward over one block-padded prompt.

    ids [1, P] (P a multiple of block_size), prompt_len traced scalar.
    Writes all P positions' K/V through `block_table` [MAXB] — padded
    tail positions resolve to slots the decode steps overwrite before
    any masked read could see them, or to the NULL block. Returns
    (first sampled token [], k_pool, v_pool)."""
    p_len = ids.shape[1]
    x = jnp.take(params["wte"], ids, axis=0)
    x = x + jnp.take(params["wpe"], jnp.arange(p_len), axis=0)

    b, s = ids.shape
    d = params["wte"].shape[1] // n_head

    def body(carry, bp):
        h = _layer_norm(carry, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = h @ bp["qkv_w"] + bp["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # dense causal attention over the prompt itself — the
        # training math, bit-for-bit (no pool read needed: the
        # prompt IS the whole context)
        attn = _attention(q, k, v, n_head, use_flash=False)
        attn = attn @ bp["proj_w"] + bp["proj_b"]
        h2, x2 = _residual_layer_norm(attn, carry, bp["ln2_w"],
                                      bp["ln2_b"], eps)
        ffn = h2 @ bp["fc1_w"] + bp["fc1_b"]
        ffn = jax.nn.gelu(ffn)
        ffn = ffn @ bp["fc2_w"] + bp["fc2_b"]
        out = x2 + ffn
        return out, (k.reshape(b, s, n_head, d),
                     v.reshape(b, s, n_head, d))

    x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
    # ks/vs [L, 1, P, H, D] -> scatter every position through the
    # table in one batched update per pool
    positions = jnp.arange(p_len)
    blk, off = _scatter_positions(block_table, positions, block_size)
    k_pool = k_pool.at[:, blk, off].set(
        ks[:, 0].astype(k_pool.dtype))
    v_pool = v_pool.at[:, blk, off].set(
        vs[:, 0].astype(v_pool.dtype))

    x = _layer_norm(x, params["lnf_w"], params["lnf_b"], eps)
    last = jax.lax.dynamic_index_in_dim(x[0], prompt_len - 1, axis=0,
                                        keepdims=False)
    logits = last @ params["wte"].T                    # [V]
    token = sample_tokens(logits[None], temperature[None],
                          top_k[None], seed[None])[0]
    return token, k_pool, v_pool


def decode_step(params, ids, positions, k_pool, v_pool, block_tables,
                context_lens, temperature, top_k, seeds, *, n_head,
                eps, block_size, use_kernel=False, interpret=False):
    """One generation step for the whole running batch.

    ids/positions [B]; context_lens[b] == positions[b] + 1 (this
    token included). Each layer writes this token's K/V at
    (tables[b, pos // BS], pos % BS) BEFORE attending — so the
    current token sees itself, and garbage a block-padded prefill
    left in that slot is overwritten before any read. Returns
    (next tokens [B], k_pool, v_pool)."""
    from ...incubate.nn.pallas import paged_attention as _pa

    bsz = ids.shape[0]
    hidden = params["wte"].shape[1]
    d = hidden // n_head
    scale = 1.0 / math.sqrt(d)
    x = jnp.take(params["wte"], ids, axis=0)
    x = x + jnp.take(params["wpe"], positions, axis=0)

    blk = jnp.take_along_axis(
        block_tables, (positions // block_size)[:, None], axis=1)[:, 0]
    off = positions % block_size

    def body(carry, xs):
        bp, kc, vc = xs
        h = _layer_norm(carry, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = h @ bp["qkv_w"] + bp["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(bsz, n_head, d)
        kc = kc.at[blk, off].set(
            k.reshape(bsz, n_head, d).astype(kc.dtype))
        vc = vc.at[blk, off].set(
            v.reshape(bsz, n_head, d).astype(vc.dtype))
        if use_kernel:
            attn = _pa.paged_attention(q, kc, vc, block_tables,
                                       context_lens, sm_scale=scale,
                                       interpret=interpret)
        else:
            attn = _pa.paged_attention_reference(
                q, kc, vc, block_tables, context_lens,
                sm_scale=scale)
        attn = attn.reshape(bsz, hidden)
        attn = attn @ bp["proj_w"] + bp["proj_b"]
        h2, x2 = _residual_layer_norm(attn, carry, bp["ln2_w"],
                                      bp["ln2_b"], eps)
        ffn = h2 @ bp["fc1_w"] + bp["fc1_b"]
        ffn = jax.nn.gelu(ffn)
        ffn = ffn @ bp["fc2_w"] + bp["fc2_b"]
        return x2 + ffn, (kc, vc)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], k_pool, v_pool))
    x = _layer_norm(x, params["lnf_w"], params["lnf_b"], eps)
    logits = x @ params["wte"].T                       # [B, V]
    tokens = sample_tokens(logits, temperature, top_k, seeds)
    return tokens, k_pool, v_pool
