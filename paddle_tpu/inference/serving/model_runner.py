"""GPT-2 paged-serving forward passes (prefill + single-token decode).

The serving engine never calls `GPTModel.forward` — re-running the
full prompt for every generated token is O(S^2) per request. Instead
this module owns the two compiled programs of the generation path:

  * `prefill_step` — ONE causal forward over the (block-padded)
    prompt that also scatters every position's K/V into the paged
    pools through the request's block table, and samples the first
    generated token from the last REAL prompt row.
  * `decode_step`  — one token per running sequence: embed, scan the
    layer stack reading/writing K/V through the pools, ragged paged
    attention over each request's cached context, sample.

Both reuse the training model's own math helpers (`_layer_norm`,
`_residual_layer_norm`, `_attention` from `text.models.gpt`) so the
serving path computes EXACTLY what the training forward computes —
the e2e contract is greedy tokens identical to a sequential
full-re-forward loop, and every numerical divergence between the two
paths is a bug, not noise.

Sampling is in-program and per-request: `temperature == 0` is exact
argmax (greedy), `temperature > 0` draws from the (optionally
top-k-filtered) softmax with a seed the HOST derives from (request
seed, absolute token index) — so an evicted-and-re-prefilled request
replays the same random choices it would have made uninterrupted,
whatever batch it lands in.

Functions take the raw jnp parameter tree (`extract_params`), not
Layers: the engine jits them with donated pools, and the PR-8
persistent compile cache keys their StableHLO like any other program.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...text.models.gpt import (_attention, _layer_norm,
                                _residual_layer_norm)

__all__ = ["extract_params", "prefill_step", "decode_step",
           "verify_step", "prefill_tail_step", "draft_params",
           "sample_tokens", "seed_for"]


def extract_params(model):
    """(jnp param tree, GPTConfig) from GPTForCausalLM / GPTModel."""
    gpt = getattr(model, "gpt", model)
    tree = gpt._params_tree()
    params = jax.tree_util.tree_map(
        lambda p: p._value if hasattr(p, "_value") else jnp.asarray(p),
        tree)
    return params, gpt.config


def draft_params(params, n_layers):
    """Truncated-layer twin of the target for speculative drafting:
    the first `n_layers` transformer blocks with the embedding /
    final-norm / lm-head weights shared as-is. The draft only has to
    AGREE with the target often enough to pay for its dispatches —
    verification makes the emitted stream the target's own tokens
    regardless of draft quality."""
    if n_layers < 1:
        raise ValueError(f"draft needs >= 1 layer, got {n_layers}")
    total = jax.tree_util.tree_leaves(
        params["blocks"])[0].shape[0]
    if n_layers > total:
        raise ValueError(
            f"draft layers {n_layers} > target layers {total}")
    out = dict(params)
    out["blocks"] = jax.tree_util.tree_map(
        lambda a: a[:n_layers], params["blocks"])
    return out


def seed_for(request_seed, token_index):
    """Host-side per-token sampling seed: a pure function of the
    request's seed and the ABSOLUTE position being sampled, so
    replayed decodes (eviction -> re-prefill) and different batch
    compositions draw identical randomness."""
    return (int(request_seed) * 1000003 + int(token_index)) \
        & 0x7FFFFFFF


def sample_tokens(logits, temperature, top_k, seeds):
    """Per-request next-token selection over [B, V] logits.

    temperature[b] == 0 -> exact argmax (greedy decode);
    temperature[b] > 0  -> categorical over logits/temperature with
    ranks >= top_k[b] masked out when top_k[b] > 0. The rank trick
    (double argsort) keeps k per-request and traced — `lax.top_k`
    would force one compiled program per distinct k."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def draw(lg, t, k, seed):
        ranks = jnp.argsort(jnp.argsort(-lg))
        keep = ranks < jnp.where(k > 0, k, vocab)
        lg = jnp.where(keep, lg, -jnp.inf)
        key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
        return jax.random.categorical(
            key, lg / jnp.maximum(t, 1e-6)).astype(jnp.int32)

    sampled = jax.vmap(draw)(logits, temperature, top_k, seeds)
    return jnp.where(temperature > 0, sampled, greedy)


def _scatter_positions(block_table, positions, block_size):
    """(pool block ids, in-block offsets) for a vector of token
    positions resolved through ONE request's block table."""
    return (jnp.take(block_table, positions // block_size, axis=0),
            positions % block_size)


def prefill_step(params, ids, prompt_len, k_pool, v_pool, block_table,
                 temperature, top_k, seed, *, n_head, eps, block_size):
    """Causal forward over one block-padded prompt.

    ids [1, P] (P a multiple of block_size), prompt_len traced scalar.
    Writes all P positions' K/V through `block_table` [MAXB] — padded
    tail positions resolve to slots the decode steps overwrite before
    any masked read could see them, or to the NULL block. Returns
    (first sampled token [], k_pool, v_pool)."""
    p_len = ids.shape[1]
    x = jnp.take(params["wte"], ids, axis=0)
    x = x + jnp.take(params["wpe"], jnp.arange(p_len), axis=0)

    b, s = ids.shape
    d = params["wte"].shape[1] // n_head

    def body(carry, bp):
        h = _layer_norm(carry, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = h @ bp["qkv_w"] + bp["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # dense causal attention over the prompt itself — the
        # training math, bit-for-bit (no pool read needed: the
        # prompt IS the whole context)
        attn = _attention(q, k, v, n_head, use_flash=False)
        attn = attn @ bp["proj_w"] + bp["proj_b"]
        h2, x2 = _residual_layer_norm(attn, carry, bp["ln2_w"],
                                      bp["ln2_b"], eps)
        ffn = h2 @ bp["fc1_w"] + bp["fc1_b"]
        ffn = jax.nn.gelu(ffn)
        ffn = ffn @ bp["fc2_w"] + bp["fc2_b"]
        out = x2 + ffn
        return out, (k.reshape(b, s, n_head, d),
                     v.reshape(b, s, n_head, d))

    x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
    # ks/vs [L, 1, P, H, D] -> scatter every position through the
    # table in one batched update per pool
    positions = jnp.arange(p_len)
    blk, off = _scatter_positions(block_table, positions, block_size)
    k_pool = k_pool.at[:, blk, off].set(
        ks[:, 0].astype(k_pool.dtype))
    v_pool = v_pool.at[:, blk, off].set(
        vs[:, 0].astype(v_pool.dtype))

    x = _layer_norm(x, params["lnf_w"], params["lnf_b"], eps)
    last = jax.lax.dynamic_index_in_dim(x[0], prompt_len - 1, axis=0,
                                        keepdims=False)
    logits = last @ params["wte"].T                    # [V]
    token = sample_tokens(logits[None], temperature[None],
                          top_k[None], seed[None])[0]
    return token, k_pool, v_pool


def decode_step(params, ids, positions, k_pool, v_pool, block_tables,
                context_lens, temperature, top_k, seeds, *, n_head,
                eps, block_size, use_kernel=False, interpret=False):
    """One generation step for the whole running batch.

    ids/positions [B]; context_lens[b] == positions[b] + 1 (this
    token included). Each layer writes this token's K/V at
    (tables[b, pos // BS], pos % BS) BEFORE attending — so the
    current token sees itself, and garbage a block-padded prefill
    left in that slot is overwritten before any read. Returns
    (next tokens [B], k_pool, v_pool)."""
    from ...incubate.nn.pallas import paged_attention as _pa

    bsz = ids.shape[0]
    hidden = params["wte"].shape[1]
    d = hidden // n_head
    scale = 1.0 / math.sqrt(d)
    x = jnp.take(params["wte"], ids, axis=0)
    x = x + jnp.take(params["wpe"], positions, axis=0)

    blk = jnp.take_along_axis(
        block_tables, (positions // block_size)[:, None], axis=1)[:, 0]
    off = positions % block_size

    def body(carry, xs):
        bp, kc, vc = xs
        h = _layer_norm(carry, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = h @ bp["qkv_w"] + bp["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(bsz, n_head, d)
        kc = kc.at[blk, off].set(
            k.reshape(bsz, n_head, d).astype(kc.dtype))
        vc = vc.at[blk, off].set(
            v.reshape(bsz, n_head, d).astype(vc.dtype))
        if use_kernel:
            attn = _pa.paged_attention(q, kc, vc, block_tables,
                                       context_lens, sm_scale=scale,
                                       interpret=interpret)
        else:
            attn = _pa.paged_attention_reference(
                q, kc, vc, block_tables, context_lens,
                sm_scale=scale)
        attn = attn.reshape(bsz, hidden)
        attn = attn @ bp["proj_w"] + bp["proj_b"]
        h2, x2 = _residual_layer_norm(attn, carry, bp["ln2_w"],
                                      bp["ln2_b"], eps)
        ffn = h2 @ bp["fc1_w"] + bp["fc1_b"]
        ffn = jax.nn.gelu(ffn)
        ffn = ffn @ bp["fc2_w"] + bp["fc2_b"]
        return x2 + ffn, (kc, vc)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], k_pool, v_pool))
    x = _layer_norm(x, params["lnf_w"], params["lnf_b"], eps)
    logits = x @ params["wte"].T                       # [B, V]
    tokens = sample_tokens(logits, temperature, top_k, seeds)
    return tokens, k_pool, v_pool


def verify_step(params, ids, start_positions, k_pool, v_pool,
                block_tables, context_lens, temperature, top_k,
                seeds, *, n_head, eps, block_size, use_kernel=False,
                interpret=False):
    """Speculative-decode verification: T tokens per sequence in ONE
    fixed-shape dispatch.

    ids [B, T]: slot 0 is the sequence's pending token (sampled last
    round, K/V unwritten), slots 1..T-1 the draft proposals. Token
    (b, t) sits at absolute position `start_positions[b] + t` and
    `context_lens[b] == start_positions[b] + 1` (slot 0 inclusive).
    Each layer writes all T slots' K/V through the table BEFORE the
    multi-query paged attention, so slot t sees slots 0..t (and
    nothing deeper — per-slot causal masking). Returns
    (tokens [B, T], k_pool, v_pool): tokens[b, t] is the target's
    choice for output index context_lens[b] + t, sampled with
    seeds[b, t] — the SAME position-keyed seed the k=1 engine would
    use, which is what makes acceptance token-identical for any
    temperature. Rejected slots' K/V writes land at positions beyond
    the accepted context and are overwritten by a later dispatch
    before any masked read could see them. `block_tables` may carry
    a trailing guaranteed-NULL column: positions past the table's
    real width clamp into it, so an at-cap sequence's overflow slots
    write garbage to the NULL block instead of its own live tail."""
    from ...incubate.nn.pallas import paged_attention as _pa

    bsz, t_q = ids.shape
    hidden = params["wte"].shape[1]
    d = hidden // n_head
    scale = 1.0 / math.sqrt(d)
    positions = start_positions[:, None] \
        + jnp.arange(t_q)[None, :]                     # [B, T]
    x = jnp.take(params["wte"], ids, axis=0)
    x = x + jnp.take(params["wpe"], positions, axis=0)

    maxb = block_tables.shape[1]
    slot_idx = jnp.minimum(positions // block_size, maxb - 1)
    blk = jnp.take_along_axis(block_tables, slot_idx, axis=1)
    off = positions % block_size

    def body(carry, xs):
        bp, kc, vc = xs
        h = _layer_norm(carry, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = h @ bp["qkv_w"] + bp["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(bsz, t_q, n_head, d)
        kc = kc.at[blk, off].set(
            k.reshape(bsz, t_q, n_head, d).astype(kc.dtype))
        vc = vc.at[blk, off].set(
            v.reshape(bsz, t_q, n_head, d).astype(vc.dtype))
        if use_kernel:
            attn = _pa.paged_attention_multi(
                q, kc, vc, block_tables, context_lens,
                sm_scale=scale, interpret=interpret)
        else:
            attn = _pa.paged_attention_multi_reference(
                q, kc, vc, block_tables, context_lens,
                sm_scale=scale)
        attn = attn.reshape(bsz, t_q, hidden)
        attn = attn @ bp["proj_w"] + bp["proj_b"]
        h2, x2 = _residual_layer_norm(attn, carry, bp["ln2_w"],
                                      bp["ln2_b"], eps)
        ffn = h2 @ bp["fc1_w"] + bp["fc1_b"]
        ffn = jax.nn.gelu(ffn)
        ffn = ffn @ bp["fc2_w"] + bp["fc2_b"]
        return x2 + ffn, (kc, vc)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], k_pool, v_pool))
    x = _layer_norm(x, params["lnf_w"], params["lnf_b"], eps)
    logits = x @ params["wte"].T                       # [B, T, V]
    vocab = logits.shape[-1]
    flat = sample_tokens(
        logits.reshape(bsz * t_q, vocab),
        jnp.repeat(temperature, t_q), jnp.repeat(top_k, t_q),
        seeds.reshape(bsz * t_q))
    return flat.reshape(bsz, t_q), k_pool, v_pool


def prefill_tail_step(params, ids, start, total_len, k_pool, v_pool,
                      block_table, temperature, top_k, seed, *,
                      n_head, eps, block_size):
    """Prefix-cache tail prefill: causal forward over ONLY the
    uncached tail of one request's context.

    The leading `start` tokens (a multiple of block_size) already
    have their K/V in the pools through shared table blocks; ids
    [1, Tpad] holds the tail (block-padded), whose token t sits at
    absolute position `start + t`. Each layer writes the tail's K/V
    through the table, then attends over the WHOLE paged context via
    the multi-query reference (slot t sees start + t + 1 tokens).
    Samples from the last REAL tail row (`total_len - 1 - start`).
    The tail is never empty — the engine caps sharing below the full
    context, so the sampling row always exists. Returns
    (first sampled token [], k_pool, v_pool)."""
    from ...incubate.nn.pallas import paged_attention as _pa

    t_pad = ids.shape[1]
    hidden = params["wte"].shape[1]
    d = hidden // n_head
    scale = 1.0 / math.sqrt(d)
    positions = start + jnp.arange(t_pad)
    x = jnp.take(params["wte"], ids, axis=0)
    x = x + jnp.take(params["wpe"], positions, axis=0)[None]

    blk, off = _scatter_positions(block_table, positions, block_size)

    def body(carry, xs):
        bp, kc, vc = xs
        h = _layer_norm(carry, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = h @ bp["qkv_w"] + bp["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(1, t_pad, n_head, d)
        kc = kc.at[blk, off].set(
            k[0].reshape(t_pad, n_head, d).astype(kc.dtype))
        vc = vc.at[blk, off].set(
            v[0].reshape(t_pad, n_head, d).astype(vc.dtype))
        # dense multi-query reference (T can be a whole prompt tail —
        # too long for the unrolled kernel): slot t's context is
        # (start + 1) + t tokens, cached prefix included
        attn = _pa.paged_attention_multi_reference(
            q, kc, vc, block_table[None], jnp.asarray([start + 1]),
            sm_scale=scale)
        attn = attn.reshape(1, t_pad, hidden)
        attn = attn @ bp["proj_w"] + bp["proj_b"]
        h2, x2 = _residual_layer_norm(attn, carry, bp["ln2_w"],
                                      bp["ln2_b"], eps)
        ffn = h2 @ bp["fc1_w"] + bp["fc1_b"]
        ffn = jax.nn.gelu(ffn)
        ffn = ffn @ bp["fc2_w"] + bp["fc2_b"]
        return x2 + ffn, (kc, vc)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], k_pool, v_pool))
    x = _layer_norm(x, params["lnf_w"], params["lnf_b"], eps)
    last = jax.lax.dynamic_index_in_dim(
        x[0], total_len - 1 - start, axis=0, keepdims=False)
    logits = last @ params["wte"].T                    # [V]
    token = sample_tokens(logits[None], temperature[None],
                          top_k[None], seed[None])[0]
    return token, k_pool, v_pool
