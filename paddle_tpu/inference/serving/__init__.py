"""paddle_tpu.inference.serving — the TPU-native serving engine.

Reference capability: paddle/fluid/inference (the 61k-LoC deployment
stack). Here the generation path is rebuilt around the TPU serving
designs in PAPERS.md — Ragged Paged Attention (arxiv 2604.15464) and
the Gemma-on-Cloud-TPU serving comparison (arxiv 2605.25645):

  * `kv_cache`      block-allocated paged KV cache: fixed-size blocks
                    in preallocated device pools, per-request block
                    tables, alloc/free/defrag + admission control
  * `scheduler`     continuous batching: FIFO admit / youngest-first
                    evict / preempt between fused decode dispatches
  * `model_runner`  the compiled prefill + paged decode programs
                    (gpt2), per-request in-program sampling
  * `engine`        `LLMEngine.generate()` / `add_request()`
                    streaming front end, donated decode step through
                    the persistent compile cache; ISSUE-13 lifecycle
                    (drain/export/timeout/watchdog emergency export)
  * `router`        `Router` — N health-checked threaded replicas,
                    least-loaded routing, deterministic token-exact
                    failover (ISSUE 13)

The ragged paged-attention decode kernel itself lives with its PR-8
siblings in `incubate.nn.pallas.paged_attention`.
"""
from __future__ import annotations

from .engine import EngineTimeout, LLMEngine
from .kv_cache import (BlockAllocator, NULL_BLOCK, PagedKVCache,
                       env_block_size, env_max_batch, env_pool_bytes)
from .autoscaler import Autoscaler, maybe_autoscale
from .router import Router, env_heartbeat_s, env_replicas
from .scheduler import (EngineOverloaded, Request, SamplingParams,
                        Scheduler, env_deadline_s, env_max_queue)

__all__ = ["LLMEngine", "SamplingParams", "Request", "Scheduler",
           "Router", "EngineOverloaded", "EngineTimeout",
           "PagedKVCache", "BlockAllocator", "NULL_BLOCK",
           "env_block_size", "env_max_batch", "env_pool_bytes",
           "env_max_queue", "env_deadline_s", "env_replicas",
           "env_heartbeat_s", "Autoscaler", "maybe_autoscale"]
