"""Multi-replica serving router with health-checked failover.

The fault-tolerance layer over PR-10's single engine (ROADMAP item
1's "multi-replica front end", ISSUE 13): a `Router` owns N
`LLMEngine` replicas, each stepped by its own worker thread, and
exploits the engine's position-keyed sampling seeds — any request is
a pure function of (prompt, generated-so-far, sampling), so a replica
lost mid-generation replays TOKEN-IDENTICALLY on any survivor:

    router = Router(model, replicas=2)     # PADDLE_SERVE_REPLICAS
    outs = router.generate(prompts, sampling)   # survives a replica
    router.drain(); router.shutdown()           # kill mid-flood

Routing — least-loaded by FREE KV BLOCKS net of queued demand
(`LLMEngine.load_score()` — the admission-control truth: the replica
with the most uncommitted pool absorbs the next prompt with the
least eviction pressure), deterministic lowest-index tiebreak, the
`serve_route` chaos site fired before any replica is touched. A
replica whose queue sheds (`EngineOverloaded`) falls through to the
next-least-loaded; only when EVERY healthy replica sheds does the
router shed to the caller.

Health — each engine stamps `heartbeat` at every completed dispatch
(and the router re-stamps at assignment); the wait loop marks a
replica DEAD when its worker thread crashed, its engine was fenced by
the watchdog incident hook (emergency drain-and-export), or it has
live work with a heartbeat older than `heartbeat_timeout_s`
(`PADDLE_SERVE_HEARTBEAT_S`) — a dispatch wedged inside XLA stops the
clock. Set the timeout ABOVE the worst-case single dispatch
(first-dispatch compiles included, unless the persistent cache
pre-warms them); as a backstop, a heartbeat timeout never retires
the LAST healthy replica — a slow compile on the survivor must not
cascade one wedge into total fleet loss. `serve/replica/<i>/healthy`
gauges track the fleet.

Failover — the dead replica is FENCED (its zombie thread, if it ever
wakes, no-ops instead of double-serving), its live requests export
(blocks release immediately — a dead replica's allocator still audits
clean) and replay on healthy replicas via `import_request(force=True)`
— bypassing drain gates and shed bounds, because an exported request
already holds an admission promise. `serve/failovers` counter +
`serve_failover` flight span; if NO healthy replica remains the
unplaced exports are retained in `orphan_exports` (never silently
dropped — the PTA073 class) and the wait raises.

All replicas boot off the same `serve_decode:<Model>` persistent
compile-cache entry (PR 8), so replica N is a warm start. At boot
the fleet negotiates ONE speculative-decoding config: every replica
is built from the same kwargs, but per-engine clamping (a model too
shallow for a draft twin, the kernel's window cap) can still leave
them lopsided — the router settles on the weakest replica's window
(`Router.spec_k`, `serve/spec/fleet_k`) and records the concession,
so failover replays and serve/spec/* telemetry describe one fleet.

Thread discipline: each worker wraps `engine.step()` in its replica's
`step_lock`; router-side surgery (export/drain) takes the same lock
with a BOUNDED acquire — a thread wedged inside a dispatch holds the
lock forever, and failover must work around the wedge, not join it
(the PR-9 bounded-acquire pattern). Request intake from the router
thread races only GIL-atomic deque/dict ops in the scheduler.
"""
from __future__ import annotations

import contextlib
import threading
import time

from ...core import monitor as _cmon
from ...monitor import chaos as _chaos
from ...monitor import flight as _flight
from ...monitor import trace as _trace
from .engine import EngineTimeout, LLMEngine
from .scheduler import EngineOverloaded

__all__ = ["Router", "env_replicas", "env_heartbeat_s"]


def env_replicas():
    """PADDLE_SERVE_REPLICAS — router replica count (default 1)."""
    return max(1, _flight._env_int("PADDLE_SERVE_REPLICAS", 1))


def env_heartbeat_s():
    """PADDLE_SERVE_HEARTBEAT_S — seconds without a completed
    dispatch before a busy replica is declared wedged (default 10)."""
    return max(0.1, _flight._env_float("PADDLE_SERVE_HEARTBEAT_S",
                                       10.0))


class _Replica:
    """One engine + its worker thread + its health flags."""

    def __init__(self, idx, engine):
        self.idx = idx
        self.engine = engine
        self.thread = None
        self.healthy = True
        self.dead = False          # failover completed — terminal
        self.error = None          # exception that killed the worker
        self.work = threading.Event()
        self.step_lock = threading.Lock()

    def load_score(self):
        return self.engine.load_score()


@contextlib.contextmanager
def _step_guard(rep, timeout):
    """Bounded acquire of a replica's step lock; yields whether the
    lock was actually taken. Every router-side touch of a replica's
    scheduler/allocator goes through this ONE helper so each call
    site states its on-timeout policy explicitly — intake/abort back
    off (the worker owns the engine), failover/drain proceed (the
    engine is fenced or quiesced and the holder is presumed wedged
    asleep inside a dispatch)."""
    locked = rep.step_lock.acquire(timeout=timeout)
    try:
        yield locked
    finally:
        if locked:
            rep.step_lock.release()


class _Record:
    """Router-side view of one request: survives failover by
    re-pointing `req` at the replaying replica's Request."""

    __slots__ = ("req_id", "on_token", "replica", "req")

    def __init__(self, req_id, on_token, replica, req):
        self.req_id = req_id
        self.on_token = on_token
        self.replica = replica
        self.req = req


class Router:
    """N-replica front end: least-loaded routing, heartbeat health,
    deterministic failover, graceful drain."""

    def __init__(self, model, replicas=None, heartbeat_timeout_s=None,
                 poll_s=0.002, incident_export=True, **engine_kwargs):
        n = int(replicas or env_replicas())
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        self.heartbeat_timeout_s = (
            env_heartbeat_s() if heartbeat_timeout_s is None
            else float(heartbeat_timeout_s))
        self._poll_s = float(poll_s)
        self._lock = threading.RLock()
        self._records = {}         # req_id -> _Record
        self._stop = False
        self._draining = False
        # kept for elastic scale-up (ISSUE 20): spawn_replica()
        # rebuilds an engine from the SAME recipe, so an autoscaled
        # replica is configured identically to the boot fleet
        self._model = model
        self._engine_kwargs = dict(engine_kwargs)
        self._incident_export = bool(incident_export)
        # exports that could not be replaced anywhere (no healthy
        # replica left) — retained, never silently dropped
        self.orphan_exports = []
        # live introspection: PADDLE_MONITOR_SERVE=<port> exposes
        # /metrics, /tracez, ... for the router's lifetime
        from ...monitor import server as _mserver

        _mserver.maybe_auto_serve("serving.Router")
        self._replicas = []
        for i in range(n):
            # every replica after the first warm-boots off the
            # persistent-cache entry the first one published
            eng = LLMEngine(model, **engine_kwargs)
            if incident_export:
                eng.arm_incident_export()
            rep = _Replica(i, eng)
            self._replicas.append(rep)
            _cmon.stat_set(f"serve/replica/{i}/healthy", 1)
        # -- spec-config negotiation (ISSUE 19) ----------------------
        # Failover replays any request on any survivor, and while
        # token identity holds at ANY spec window by contract, the
        # fleet must still agree on ONE config or serve/spec/*
        # telemetry and the k-aware admission promise stop meaning
        # anything. All replicas are built from the same kwargs, so
        # disagreement can only come from per-engine clamping (model
        # too shallow for a draft twin, window capped at the kernel
        # limit) — negotiate down to the weakest replica and record
        # the concession instead of serving a lopsided fleet.
        ks = sorted({r.engine.spec_k for r in self._replicas})
        pcs = {bool(r.engine.prefix_cache) for r in self._replicas}
        self.spec_k = ks[0]
        self.prefix_cache = pcs == {True}
        if len(ks) > 1 or len(pcs) > 1:
            _flight.record("serve_spec_negotiate",
                           spec_ks=ks, negotiated=self.spec_k,
                           prefix=sorted(pcs))
        if self.spec_k > 1:
            _cmon.stat_set("serve/spec/fleet_k", self.spec_k)
        for rep in self._replicas:
            t = threading.Thread(
                target=self._replica_loop, args=(rep,),
                name=f"serve-replica-{rep.idx}", daemon=True)
            rep.thread = t
            t.start()
        # observability -> capacity loop (ISSUE 20): None unless
        # PADDLE_SERVE_AUTOSCALE arms it — zero listeners, zero
        # serve/autoscale/* stats, bit-identical serving otherwise
        from . import autoscaler as _autoscaler

        self.autoscaler = _autoscaler.maybe_autoscale(self)

    # -- worker loop -------------------------------------------------
    def _replica_loop(self, rep):
        eng = rep.engine
        while not self._stop:
            if rep.dead or eng.fenced:
                return
            idle = (not eng.has_unfinished()
                    or (eng.scheduler.draining
                        and not eng.scheduler.running))
            if idle:
                rep.work.clear()
                # re-check after clear so a submit racing the clear
                # costs one bounded wait, never a lost wakeup
                if not eng.has_unfinished() \
                        or (eng.scheduler.draining
                            and not eng.scheduler.running):
                    rep.work.wait(timeout=0.05)
                continue
            try:
                with rep.step_lock:
                    if rep.dead or eng.fenced:
                        return
                    eng.step()
            except Exception as e:
                # the wait loop turns this into a failover; flags
                # only (no router lock from a worker — one-way lock
                # order: router lock -> step_lock)
                rep.error = e
                rep.healthy = False
                _cmon.stat_set(
                    f"serve/replica/{rep.idx}/healthy", 0)
                _flight.record("serve_replica_error",
                               replica=rep.idx,
                               error=f"{type(e).__name__}: {e}")
                return

    # -- routing -----------------------------------------------------
    def _live(self):
        """Replicas that can accept work: healthy, not failed over,
        and not fenced (a watchdog-fenced engine no-ops step() — a
        request routed there before the next health pass would be
        stranded on a dead queue)."""
        return [r for r in self._replicas
                if r.healthy and not r.dead
                and not r.engine.fenced]

    def _pick_replica(self, exclude=()):
        """Healthy replica with the most free KV blocks (least
        loaded), lowest index on ties — deterministic. Fires the
        `serve_route` chaos site BEFORE touching any replica."""
        cands = [r for r in self._live() if r not in exclude]
        if not cands:
            raise RuntimeError(
                "no healthy serving replicas "
                f"({len(self._replicas)} configured, all dead)")
        if _chaos._armed:
            _chaos.hit("serve_route", candidates=len(cands))
        return max(cands, key=lambda r: (r.load_score(), -r.idx))

    def submit(self, prompt_ids, sampling=None, on_token=None,
               req_id=None):
        """Route one request to the least-loaded healthy replica;
        returns its req_id. A replica that sheds (queue full) falls
        through to the next; when every healthy replica sheds, the
        router sheds to the caller (EngineOverloaded)."""
        with self._lock:
            tried = []
            while True:
                try:
                    rep = self._pick_replica(exclude=tried)
                except RuntimeError as e:
                    if tried:
                        # a replica died between the shed fall-
                        # through and this pick: at least one
                        # healthy replica shed, so the caller-
                        # visible contract stays the retryable
                        # EngineOverloaded, not a fleet-death error
                        raise EngineOverloaded(
                            "every remaining replica shed or died "
                            "mid-submit — router overloaded",
                            engine_state=self.state_summary()) from e
                    raise
                # intake mutates the replica's scheduler (queue
                # append, expiry sweep on a full queue) — serialize
                # against its worker's step() like every other
                # router-side surgery; a replica too wedged to hand
                # over the lock is treated as shedding
                try:
                    with _step_guard(rep, 1.0) as locked:
                        if not locked:
                            raise EngineOverloaded(
                                f"replica {rep.idx} step lock busy")
                        was_idle = not rep.engine.scheduler.has_work()
                        rid = rep.engine.add_request(
                            prompt_ids, sampling=sampling,
                            on_token=on_token, req_id=req_id)
                except EngineOverloaded as e:
                    tried.append(rep)
                    if len(tried) >= len(self._live()):
                        raise EngineOverloaded(
                            f"all {len(tried)} healthy replicas "
                            "shed — router overloaded",
                            engine_state=self.state_summary()) from e
                    continue
                rec = _Record(rid, on_token, rep.idx,
                              rep.engine.get_request(rid))
                self._records[rid] = rec
                if _trace._armed:
                    _trace.note(rec.req, "route", replica=rep.idx)
                # reset the wedge clock ONLY on the idle->work
                # transition (an engine idle for an hour is not
                # wedged the moment work lands) — a busy replica
                # must keep its clock, or steady traffic landing on
                # a wedged one would postpone detection forever
                if was_idle:
                    rep.engine.heartbeat = time.monotonic()
                _flight.record("serve_route", req=rid,
                               replica=rep.idx,
                               load_score=rep.load_score())
                rep.work.set()
                return rid

    # -- health / failover -------------------------------------------
    def _check_health(self):
        with self._lock:
            for rep in self._replicas:
                if rep.dead:
                    continue
                eng = rep.engine
                if rep.error is not None:
                    self._failover(rep, f"crash: "
                                   f"{type(rep.error).__name__}: "
                                   f"{rep.error}")
                elif eng.fenced:
                    # watchdog incident hook already fenced+exported
                    self._failover(rep, "incident_export")
                elif eng.scheduler.has_work() and \
                        eng.heartbeat_age() > self.heartbeat_timeout_s \
                        and len(self._live()) > 1:
                    # a heartbeat timeout never retires the LAST
                    # healthy replica: its exports would have nowhere
                    # to replay, and a slow-but-alive dispatch (a
                    # post-failover prefill bucket compiling for the
                    # first time) would otherwise cascade one wedge
                    # into total fleet loss. Real crashes and
                    # watchdog fences still retire it (orphan
                    # retention takes over).
                    self._failover(rep, "heartbeat_timeout")

    def _failover(self, rep, reason):
        """Retire a dead/wedged replica and replay its in-flight
        requests on the survivors, token-identically (caller holds
        the router lock). Exports that cannot be placed are retained
        in `orphan_exports`, never dropped."""
        rep.healthy = False
        _cmon.stat_set(f"serve/replica/{rep.idx}/healthy", 0)
        with _flight.in_flight("serve_failover",
                               f"replica-{rep.idx}", reason=reason):
            # fence FIRST: a live-but-slow worker (false-positive
            # heartbeat) parks after its current step instead of
            # mutating scheduler state under the export
            eng = rep.engine
            eng._fenced = True
            # bounded grace for a slow-but-live step to finish and
            # observe the fence; a thread wedged INSIDE a dispatch
            # holds the step lock forever and failover must work
            # around the wedge (it's fenced, so a zombie waking
            # later no-ops), not join it — proceed either way
            with _step_guard(rep, 1.25):
                exports = eng.emergency_exports or []
                eng.emergency_exports = None
                # sweep AGAIN even when the incident hook already
                # exported: a request routed here between the fence
                # and this failover pass sits in the scheduler the
                # hook's export never saw
                exports = exports + eng.export_requests(fence=True)
            rep.dead = True
            rep.work.set()          # unpark the worker so it exits
            _cmon.stat_add("serve/failovers", 1)
            _flight.record("serve_failover", replica=rep.idx,
                           reason=str(reason)[:200],
                           exported=len(exports))
            self._replay(exports, rep, reason)

    def _replay(self, exports, rep, reason):
        """Replay exported requests on the survivors,
        token-identically (caller holds the router lock; `rep` is
        the retired source replica). Shared by crash failover and
        planned scale-down — the SAME placement loop, so a drained
        replica's requests land exactly where a crashed one's would.
        Exports that cannot be placed are retained in
        `orphan_exports`, never dropped."""
        for i, exp in enumerate(exports):
            rec = self._records.get(exp["req_id"])
            excluded = []
            while True:
                try:
                    target = self._pick_replica(exclude=excluded)
                except RuntimeError:
                    # nowhere to replay: retain, never drop
                    self.orphan_exports.extend(exports[i:])
                    raise
                try:
                    was_idle = not \
                        target.engine.scheduler.has_work()
                    rid = target.engine.import_request(
                        exp,
                        on_token=rec.on_token if rec else None,
                        force=True)
                except EngineOverloaded:
                    # target got fenced between the pick and
                    # the import (concurrent incident hook) —
                    # try the next survivor
                    excluded.append(target)
                    continue
                break
            if rec is not None:
                rec.replica = target.idx
                rec.req = target.engine.get_request(rid)
            if _trace._armed:
                _trace.note(target.engine.get_request(rid),
                            "failover", from_replica=rep.idx,
                            to_replica=target.idx,
                            reason=str(reason)[:80])
            if was_idle:     # idle->work only, as in submit()
                target.engine.heartbeat = time.monotonic()
            target.work.set()

    # -- elastic capacity (ISSUE 20) ---------------------------------
    def spawn_replica(self):
        """Scale UP by one replica; returns its index, or None when
        the router is stopping/draining. The engine builds OUTSIDE
        the router lock — boot is a warm start off the
        `serve_decode:<Model>` persistent-cache entry the first
        replica published, but even a cache load must not stall
        submit/health traffic — then joins the fleet under the lock
        with the same spec negotiation the boot fleet ran."""
        if self._stop or self._draining:
            return None
        eng = LLMEngine(self._model, **self._engine_kwargs)
        if self._incident_export:
            eng.arm_incident_export()
        with self._lock:
            if self._stop or self._draining:
                return None
            idx = len(self._replicas)
            rep = _Replica(idx, eng)
            # fleet spec config only ever negotiates DOWN (ISSUE
            # 19): a newcomer clamped below the fleet drags the
            # fleet to its window; a roomier one adopts the fleet's
            if eng.spec_k < self.spec_k:
                _flight.record("serve_spec_negotiate",
                               spec_ks=[self.spec_k, eng.spec_k],
                               negotiated=eng.spec_k,
                               scope="spawn")
                self.spec_k = eng.spec_k
            if self.spec_k > 1:
                _cmon.stat_set("serve/spec/fleet_k", self.spec_k)
            self.prefix_cache = (self.prefix_cache
                                 and bool(eng.prefix_cache))
            self._replicas.append(rep)
            _cmon.stat_set(f"serve/replica/{idx}/healthy", 1)
            t = threading.Thread(
                target=self._replica_loop, args=(rep,),
                name=f"serve-replica-{idx}", daemon=True)
            rep.thread = t
            t.start()
        _flight.record("serve_scale_up", replica=idx,
                       replicas=len(self._replicas))
        return idx

    def retire_replica(self, idx=None):
        """Scale DOWN by one replica (default: the newest live one)
        via the token-exact export path: fence, export its in-flight
        requests, replay them on the survivors — callers see
        identical tokens, just from elsewhere. Refuses to retire the
        last healthy replica. Returns the retired index."""
        with self._lock:
            live = self._live()
            if len(live) <= 1:
                raise RuntimeError(
                    "refusing to retire the last healthy replica")
            rep = (max(live, key=lambda r: r.idx) if idx is None
                   else self._replicas[idx])
            if rep not in live:
                raise RuntimeError(
                    f"replica {rep.idx} is not live — nothing to "
                    "retire")
            rep.healthy = False
            _cmon.stat_set(f"serve/replica/{rep.idx}/healthy", 0)
            with _flight.in_flight("serve_scale_down",
                                   f"replica-{rep.idx}"):
                # same fence-then-bounded-sweep as _failover: the
                # worker parks after its current step, a wedged one
                # is worked around (fenced zombies no-op)
                eng = rep.engine
                eng._fenced = True
                with _step_guard(rep, 1.25):
                    exports = eng.emergency_exports or []
                    eng.emergency_exports = None
                    exports = exports + eng.export_requests(
                        fence=True)
                rep.dead = True
                rep.work.set()      # unpark the worker so it exits
                _flight.record("serve_scale_down", replica=rep.idx,
                               exported=len(exports),
                               replicas=len(self._live()))
                self._replay(exports, rep, "scale_down")
            return rep.idx

    # -- completion --------------------------------------------------
    def wait(self, ids=None, timeout_s=None):
        """Block until every tracked (or listed) request reaches a
        terminal state, running health checks + failover as it polls.
        Raises EngineTimeout (router state attached) on timeout —
        never hangs on a wedged fleet."""
        ids = list(self._records) if ids is None else list(ids)
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        while True:
            self._check_health()
            recs = [self._records[i] for i in ids]
            if all(r.req.finished for r in recs):
                return
            if deadline is not None and time.monotonic() > deadline:
                raise EngineTimeout(
                    f"router wait() exceeded timeout_s={timeout_s} "
                    f"with {sum(not r.req.finished for r in recs)} "
                    "request(s) live",
                    engine_state=self.state_summary())
            time.sleep(self._poll_s)

    def generate(self, prompts, sampling=None, timeout_s=None):
        """Submit `prompts` across the fleet and wait to drain;
        returns each prompt's generated ids in order. Survives
        replica loss mid-flood with token-identical outputs."""
        ids = [self.submit(p, sampling=sampling) for p in prompts]
        self.wait(ids, timeout_s=timeout_s)
        outs = [self._records[i].req.output_ids for i in ids]
        for i in ids:
            self.release(i)
        return outs

    def get_request(self, req_id):
        """The LIVE Request object (follows failover re-homing)."""
        return self._records[req_id].req

    def release(self, req_id):
        """Drop the router record + the owning replica's retained
        result for a finished request."""
        rec = self._records.get(req_id)
        if rec is None or not rec.req.finished:
            return
        # finished-only: terminal states released their blocks at
        # scheduler.finish time, this only drops host records
        del self._records[req_id]
        for rep in self._replicas:
            rep.engine.release_request(req_id)

    def abort(self, req_id):
        """Cancel a live request. Backs off (EngineOverloaded) when
        the owning replica's worker holds its step lock past the
        bound — aborting UNLOCKED would free the request's KV blocks
        under an in-flight dispatch that still reads them (the
        PTA071 class); retry, or let failover reap the replica."""
        rec = self._records.get(req_id)
        if rec is None or rec.req.finished:
            return
        with self._lock:
            rep = self._replicas[rec.replica]
            with _step_guard(rep, 1.0) as locked:
                if not locked:
                    raise EngineOverloaded(
                        f"replica {rep.idx} is busy (step lock held "
                        f"past bound) — retry abort({req_id!r})",
                        engine_state=self.state_summary())
                rep.engine.abort_request(req_id)

    # -- lifecycle ---------------------------------------------------
    def drain(self, timeout_s=None):
        """Graceful router drain: stop admitting fleet-wide (new
        `submit` sheds), let RUNNING requests complete, export the
        leftovers. Returns the combined export list; `resume()`
        re-opens admission."""
        with _flight.in_flight("serve_drain", "router",
                               replicas=len(self._live())):
            if _chaos._armed:
                _chaos.hit("serve_drain", scope="router")
            with self._lock:
                self._draining = True
                live = self._live()
                for rep in live:
                    rep.engine.scheduler.draining = True
            deadline = (time.monotonic() + timeout_s
                        if timeout_s is not None else None)
            while any(rep.engine.scheduler.running for rep in live):
                self._check_health()
                if deadline is not None \
                        and time.monotonic() > deadline:
                    break
                time.sleep(self._poll_s)
            exports = []
            with self._lock:
                # sweep every NON-DEAD replica, fenced ones
                # included: a replica the incident hook fenced after
                # the last health pass holds its in-flight work in
                # emergency_exports, and skipping it here would
                # neither return nor fail over those requests
                for rep in self._replicas:
                    if rep.dead:
                        continue
                    with _step_guard(rep, 1.0):
                        em = rep.engine.emergency_exports
                        if em:
                            rep.engine.emergency_exports = None
                            exports.extend(em)
                        exports.extend(
                            rep.engine.export_requests(fence=False))
            _cmon.stat_add("serve/drains", 1)
            _flight.record("serve_drain_done", scope="router",
                           exported=len(exports))
        return exports

    def resume(self):
        """Re-open admission after drain() on every surviving
        replica."""
        with self._lock:
            self._draining = False
            for rep in self._live():
                rep.engine.resume()
                rep.work.set()

    def shutdown(self, timeout_s=2.0):
        """Stop worker threads, disarm incident hooks. Engines stay
        readable (results, audits) but nothing steps anymore."""
        self._stop = True
        if self.autoscaler is not None:
            self.autoscaler.detach()
        for rep in self._replicas:
            rep.work.set()
        for rep in self._replicas:
            if rep.thread is not None:
                rep.thread.join(timeout=timeout_s)
        for rep in self._replicas:
            rep.engine.disarm_incident_export()

    # -- trace spool (ISSUE 15) --------------------------------------
    def export_traces(self):
        """Fleet-wide trace spool: every replica's retained requests,
        each entry tagged with its replica index. A failed-over
        request appears once per engine leg (same trace_id) — the
        exporting replica's story up to EXPORTED plus the survivor's
        import-and-replay continuation."""
        entries = []
        for rep in self._replicas:
            spool = _trace.export_requests(
                rep.engine._requests.values(),
                extra={"replica": rep.idx})
            entries.extend(spool["requests"])
        out = _trace.export_requests(())
        out["requests"] = entries
        return out

    def dump_traces(self, path):
        import json

        with open(path, "w") as f:
            json.dump(self.export_traces(), f, default=str)
        return path

    # -- introspection -----------------------------------------------
    def replica_healthy(self, idx):
        rep = self._replicas[idx]
        return rep.healthy and not rep.dead

    def state_summary(self):
        return {
            "replicas": len(self._replicas),
            "healthy": len(self._live()),
            "spec_k": self.spec_k,
            "prefix_cache": self.prefix_cache,
            "draining": self._draining,
            "records": len(self._records),
            "orphan_exports": len(self.orphan_exports),
            "engines": [r.engine.state_summary()
                        for r in self._replicas],
        }

    def check_drained(self):
        """Zero-leak audit over the WHOLE fleet — dead replicas
        included (export releases their blocks host-side)."""
        leaks = {}
        for rep in self._replicas:
            for owner, blocks in rep.engine.check_drained().items():
                leaks[f"replica{rep.idx}:{owner}"] = blocks
        return leaks
