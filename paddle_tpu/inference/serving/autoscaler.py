"""Router replica autoscaler — the first closed-loop consumer of the
SLO alert engine (ISSUE 20).

monitor.alerts turns the serving histograms into
pending->firing->resolved transitions; this module turns those
transitions into CAPACITY. An `Autoscaler` attached to a Router
subscribes to alert transitions (alerts.add_listener) and:

  * on `fire` of its target rule (default ttft_p99, the p99-TTFT
    SLO): spawns one replica via Router.spawn_replica() — a WARM
    start off the `serve_decode:<Model>` persistent compile-cache
    entry the first replica published (PR 8), so added capacity
    costs a cache load, not a recompile;
  * on `resolve`: drains one replica back toward `min_replicas` via
    Router.retire_replica() — the PR-13 token-exact export path, so
    in-flight requests replay on the survivors with IDENTICAL
    tokens.

Hysteresis lives in three places: the alert's own for/clear streaks
(no action on a single bad tick), the `cooldown_s` floor between ANY
two scaling actions (a storm that fires+resolves+fires inside the
cooldown moves capacity once, not thrice), and the min/max replica
clamps. One step per transition — the alert re-fires on the next
evaluation tick if one replica wasn't enough, so convergence is
rate-limited by the evaluator cadence, never a thundering spawn
herd.

Telemetry: `serve/autoscale/{spawns,drains,replicas,suppressed}` +
`autoscale_up`/`autoscale_down` flight events. Armed by
PADDLE_SERVE_AUTOSCALE (Router.__init__ calls maybe_autoscale();
falsy/unset = no Autoscaler object, zero counters, zero listeners —
the house provenance contract) or explicitly:

    scaler = Autoscaler(router, max_replicas=4).attach()
"""
from __future__ import annotations

import os
import threading
import time

from ...core import monitor as _cmon
from ...monitor import alerts as _alerts
from ...monitor import flight as _flight

__all__ = ["Autoscaler", "maybe_autoscale", "env_autoscale_rule",
           "env_min_replicas", "env_max_replicas", "env_cooldown_s"]


def env_autoscale_rule():
    """PADDLE_SERVE_AUTOSCALE — falsy/unset disarms; `1`/`on`/`true`
    arms against the default `ttft_p99` rule; any other value names
    the alert rule to scale against."""
    v = os.environ.get("PADDLE_SERVE_AUTOSCALE", "").strip()
    if not v or v.lower() in _flight._FALSY:
        return None
    if v.lower() in ("1", "on", "true"):
        return "ttft_p99"
    return v


def env_min_replicas():
    """PADDLE_SERVE_AUTOSCALE_MIN — scale-down floor (default 0 =
    keep the router's boot-time replica count)."""
    return max(0, _flight._env_int("PADDLE_SERVE_AUTOSCALE_MIN", 0))


def env_max_replicas():
    """PADDLE_SERVE_AUTOSCALE_MAX — scale-up ceiling (default 4)."""
    return max(1, _flight._env_int("PADDLE_SERVE_AUTOSCALE_MAX", 4))


def env_cooldown_s():
    """PADDLE_SERVE_AUTOSCALE_COOLDOWN_S — floor between scaling
    actions (default 30s)."""
    return max(0.0, _flight._env_float(
        "PADDLE_SERVE_AUTOSCALE_COOLDOWN_S", 30.0))


class Autoscaler:
    """Alert-transition -> replica-count controller for one Router.

    Runs entirely on the alert evaluator's notification callback (no
    thread of its own): spawn/drain are bounded-latency router calls
    and the evaluator cadence IS the control loop period."""

    def __init__(self, router, rule="ttft_p99", min_replicas=None,
                 max_replicas=None, cooldown_s=None):
        self.router = router
        self.rule = str(rule)
        boot = len(router._replicas)
        self.min_replicas = (boot if not min_replicas
                             else max(1, int(min_replicas)))
        self.max_replicas = (env_max_replicas()
                             if max_replicas is None
                             else max(1, int(max_replicas)))
        self.cooldown_s = (env_cooldown_s() if cooldown_s is None
                           else max(0.0, float(cooldown_s)))
        self._lock = threading.Lock()
        self._last_action = None
        self._attached = False

    # -- lifecycle ---------------------------------------------------
    def attach(self):
        """Subscribe to alert transitions; publishes the replicas
        gauge so a fleet scrape shows autoscaling is live."""
        if not self._attached:
            _alerts.add_listener(self._on_alert)
            self._attached = True
            _cmon.stat_set("serve/autoscale/replicas",
                           len(self.router._live()))
        return self

    def detach(self):
        if self._attached:
            _alerts.remove_listener(self._on_alert)
            self._attached = False

    def attached(self):
        return self._attached

    # -- control loop ------------------------------------------------
    def _on_alert(self, rule, transition, value):
        if rule.name != self.rule:
            return
        with self._lock:
            if transition == "fire":
                self.scale_up(value=value)
            elif transition == "resolve":
                self.scale_down(value=value)

    def _cooled(self, now):
        return (self._last_action is None
                or now - self._last_action >= self.cooldown_s)

    def scale_up(self, value=None, now=None):
        """One replica up (clamped at max_replicas, cooldown-gated).
        Returns the new replica index or None when suppressed."""
        now = time.monotonic() if now is None else now
        live = len(self.router._live())
        if live >= self.max_replicas or not self._cooled(now):
            _cmon.stat_add("serve/autoscale/suppressed", 1)
            return None
        idx = self.router.spawn_replica()
        if idx is None:       # router draining/stopped
            return None
        self._last_action = now
        _cmon.stat_add("serve/autoscale/spawns", 1)
        _cmon.stat_set("serve/autoscale/replicas",
                       len(self.router._live()))
        _flight.record("autoscale_up", replica=idx, rule=self.rule,
                       value=value)
        return idx

    def scale_down(self, value=None, now=None):
        """One replica down toward min_replicas (cooldown-gated,
        token-exact drain). Returns the retired index or None."""
        now = time.monotonic() if now is None else now
        if len(self.router._live()) <= self.min_replicas \
                or not self._cooled(now):
            return None
        try:
            idx = self.router.retire_replica()
        except RuntimeError:
            # lost the race to a failover — the fleet is already at
            # one healthy replica, nothing to drain
            return None
        self._last_action = now
        _cmon.stat_add("serve/autoscale/drains", 1)
        _cmon.stat_set("serve/autoscale/replicas",
                       len(self.router._live()))
        _flight.record("autoscale_down", replica=idx,
                       rule=self.rule, value=value)
        return idx

    def describe(self):
        return {"rule": self.rule, "attached": self._attached,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "cooldown_s": self.cooldown_s,
                "live": len(self.router._live())}


def maybe_autoscale(router):
    """Router boot hook: attach an Autoscaler iff
    PADDLE_SERVE_AUTOSCALE names/arms a rule. Disarmed -> None (no
    object, no listener, no serve/autoscale/* stats — bit-identical
    serving)."""
    rule = env_autoscale_rule()
    if rule is None:
        return None
    return Autoscaler(
        router, rule=rule,
        min_replicas=env_min_replicas() or None,
        max_replicas=env_max_replicas(),
        cooldown_s=env_cooldown_s()).attach()
