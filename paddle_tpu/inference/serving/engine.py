"""LLMEngine — the TPU-native generation front end.

The user surface of the serving subsystem (ROADMAP item 1): a
GPTForCausalLM plus a paged KV cache, a continuous-batching
scheduler, and two compiled programs — per-bucket prefill and ONE
fixed-shape decode step covering all `max_batch` slots — that
together serve many concurrent mixed-length requests:

    engine = LLMEngine(model)
    engine.add_request([1, 2, 3], SamplingParams(max_new_tokens=8),
                       on_token=stream_cb)          # streaming
    outs = engine.generate([[1, 2, 3], [7, 8]])     # run-to-drain

Per engine `step()`: admit+prefill whatever the scheduler lets in,
grow block tables across block boundaries (evicting under pool
pressure), then ONE decode dispatch for the whole batch — inactive
slots ride along pointed at the NULL block. Stop conditions
(eos/stop ids/max_new_tokens/max_seq_len) apply host-side on the
returned tokens; finished requests free their blocks before the next
admission pass.

Compiled-step contract: the decode step is `jax.jit` with BOTH pools
DONATED (the engine re-adopts the returned pools each dispatch — the
PR-8/PR-9 donation discipline), and its first dispatch routes
through the persistent compile cache (`jit.persistent_cache`) under
the label `serve_decode:<Model>` — a serving replica restarting
against a warm PADDLE_SERVE-sized pool skips the backend compile
entirely (the ROADMAP cold-start story). Prefill compiles once per
block-rounded prompt-length bucket, so prompt-length cardinality is
`max_seq_len / block_size`, not `max_seq_len`.

Failure path: a RESOURCE_EXHAUSTED dispatch (real, or injected at
the `serve_decode` chaos site) evicts the youngest request and
retries — serving degrades to a smaller batch instead of dying.

Speculative decoding (`spec_k`/`PADDLE_SERVE_SPEC_K` > 1): a small
DRAFT model — by default the target's first `draft_layers` blocks
sharing its embeddings and head (`model_runner.draft_params`) —
proposes k-1 tokens with k cheap batched dispatches against its own
twin pools, then ONE fixed-shape `verify_step` dispatch runs the
target over all k slots (pending token + proposals) via the
multi-query paged-attention kernel. The engine emits the longest
prefix of proposals that AGREE with the target's own position-seeded
choices, plus the first disagreeing target token — rejection-free
greedy verification: every emitted token is the target's own choice
for its position, so the stream is token-identical to k=1 at ANY
temperature, and a bad draft only costs speed (1..k tokens per
verify). `serve/spec/{proposed,accepted}` + `serve/hist/accept_len`
price the win. Spec paths dispatch through block tables widened by
one guaranteed-NULL column so near-`max_seq_len` overflow slots
clamp their garbage writes into the null block.

Prefix caching (`prefix_cache`/`PADDLE_SERVE_PREFIX_CACHE`): full
immutable prompt blocks are content-hashed after prefill; a later
request whose prompt chains onto published blocks admits with those
blocks mapped copy-on-write and prefills ONLY the uncached tail
(`prefill_tail_step`), with `serve/prefix/{hits,blocks_shared,
prefill_tokens_saved}` counting the saved work. Both features are
OFF by default and their disarmed paths leave the k=1 decode/prefill
programs untouched (the HLO-identity bench contract).

Lifecycle (ISSUE 13 — the failure-policy ring):

  * `drain(timeout_s)` — stop admitting (new intake sheds with
    `EngineOverloaded`), run RUNNING requests to completion, then
    EXPORT whatever is left (prompt + generated-so-far + sampling)
    for token-exact re-admission elsewhere (`import_request` —
    position-keyed sampling seeds make replay deterministic on ANY
    engine). `serve/drains`, `serve_drain` chaos site + flight span.
  * `generate(timeout_s=)` — raises `EngineTimeout` with the engine
    state summary attached instead of hanging to drain forever. The
    bound is judged BETWEEN dispatches; a dispatch wedged inside XLA
    is the watchdog's jurisdiction, which is why
  * `arm_incident_export()` registers a PR-3/6 incident hook: a
    watchdog-detected wedge (stuck `serve_decode` span) fences the
    engine and performs an emergency drain-and-export — in-flight
    requests become `emergency_exports` a router/operator replays on
    a healthy replica instead of dying with the wedged one.
  * A FENCED engine (`_fenced`) no-ops `step()`: after a failover
    exported its requests, a zombie thread waking from the wedge
    cannot double-serve them.

Telemetry: `serve/{requests,tokens,prefill_us,decode_us,evictions,
queue_depth,drains,kv_blocks/*}` counters plus `serve_prefill`/
`serve_decode`/`serve_drain` flight spans, all through the PR-1/PR-3
monitor hub. `heartbeat` is stamped at every completed dispatch —
the router's per-replica health signal.
"""
from __future__ import annotations

import functools
import math
import time

import numpy as np

from ...core import monitor as _cmon
from ...monitor import chaos as _chaos
from ...monitor import flight as _flight
from ...monitor import perf as _perf
from ...monitor import sanitize as _san
from ...monitor import trace as _trace
from . import model_runner as _mr
from .kv_cache import (NULL_BLOCK, PagedKVCache, env_max_batch,
                       env_prefix_cache, env_spec_draft, env_spec_k)
from .scheduler import (EngineOverloaded, EXPORTED, FINISHED,
                        Request, SamplingParams, Scheduler)

__all__ = ["LLMEngine", "EngineTimeout"]


class EngineTimeout(TimeoutError):
    """`generate(timeout_s=)` ran out of budget with work still live.
    Carries the engine's state summary in `.engine_state` — what was
    waiting/running and how stale the heartbeat was, so the caller
    (or the incident report) sees WHERE generation stood instead of
    a bare hang-turned-timeout."""

    def __init__(self, msg, engine_state=None):
        super().__init__(msg)
        self.engine_state = engine_state or {}


class LLMEngine:
    """Continuous-batching generation engine over one causal LM."""

    def __init__(self, model, max_batch=None, block_size=None,
                 num_blocks=None, pool_bytes=None, dtype=None,
                 static_batching=False, use_kernel=None,
                 donate=True, max_queue=None, spec_k=None,
                 draft_layers=None, prefix_cache=None):
        import jax

        self.params, self.config = _mr.extract_params(model)
        cfg = self.config
        self.max_batch = int(max_batch or env_max_batch())
        self.max_seq_len = int(cfg.max_seq_len)
        head_dim = cfg.hidden_size // cfg.num_heads
        # speculative-decode width: 1 = off (the verify kernel
        # unrolls its query slots, so k is capped at 8)
        self.spec_k = max(1, min(
            8, int(spec_k if spec_k is not None else env_spec_k())))
        if self.spec_k > 1:
            n_draft = int(draft_layers if draft_layers is not None
                          else env_spec_draft())
            if n_draft <= 0:         # auto: half the target's depth
                n_draft = max(1, cfg.num_layers // 2)
            self.draft_layers = min(n_draft, cfg.num_layers)
        else:
            self.draft_layers = 0
        self.prefix_cache = bool(
            prefix_cache if prefix_cache is not None
            else env_prefix_cache())
        self.cache = PagedKVCache(
            cfg.num_layers, cfg.num_heads, head_dim,
            block_size=block_size, num_blocks=num_blocks,
            pool_bytes=pool_bytes, dtype=dtype,
            draft_layers=self.draft_layers,
            prefix_cache=self.prefix_cache)
        self.block_size = self.cache.block_size
        # fixed table width: enough slots for a max-length sequence
        self.max_blocks_per_seq = math.ceil(
            self.max_seq_len / self.block_size)
        self.scheduler = Scheduler(self.cache, self.max_batch,
                                   self.max_seq_len,
                                   static_batching=static_batching,
                                   max_queue=max_queue,
                                   spec_tokens=self.spec_k)
        self._requests = {}          # req_id -> Request (all states)
        if use_kernel is None:
            from ...incubate.nn import pallas as _pl

            use_kernel = _pl.kernels_available() and \
                _pl.paged_attention.paged_decode_supported(
                    head_dim, self.block_size)
            self._kernel_interpret = _pl.interpret_mode()
        else:
            self._kernel_interpret = False
        self.use_kernel = bool(use_kernel)
        self._donate = bool(donate)

        decode = functools.partial(
            _mr.decode_step, n_head=cfg.num_heads,
            eps=cfg.layer_norm_eps, block_size=self.block_size,
            use_kernel=self.use_kernel,
            interpret=self._kernel_interpret)
        self._decode_jit = jax.jit(
            decode, donate_argnums=(3, 4) if self._donate else ())
        self._decode_exe = None      # persistent-cache hit, if any
        self._prefill_jits = {}      # padded len -> jitted prefill
        # -- speculative-decode programs (spec_k > 1 only; the k=1
        # decode program above stays byte-identical either way)
        self._draft_params = None
        self._verify_jit = self._draft_jit = None
        self._draft_prefill_jits = {}
        if self.spec_k > 1:
            self._draft_params = _mr.draft_params(self.params,
                                                  self.draft_layers)
            verify = functools.partial(
                _mr.verify_step, n_head=cfg.num_heads,
                eps=cfg.layer_norm_eps, block_size=self.block_size,
                use_kernel=self.use_kernel,
                interpret=self._kernel_interpret)
            self._verify_jit = jax.jit(
                verify,
                donate_argnums=(3, 4) if self._donate else ())
            # a separate jit instance for the draft's decode steps:
            # its donations consume the DRAFT pools, never the
            # target's
            self._draft_jit = jax.jit(
                decode,
                donate_argnums=(3, 4) if self._donate else ())
            _cmon.stat_set("serve/spec/k", self.spec_k)
        # prefix-cache tail-prefill programs (tail length bucketed)
        self._tail_jits = {}
        self._draft_tail_jits = {}
        self._pcache_label = (
            f"serve_decode:{type(model).__name__}")
        self._prefill_label = (
            f"serve_prefill:{type(model).__name__}")
        # padded len -> ledger ordinal: each prefill bucket is its
        # own compiled program and gets its own perf/program entry
        # (first bucket keeps the plain label, later ones "#n" —
        # the jit shape-specialization naming)
        self._prefill_captured = {}
        self._oom_streak = 0         # consecutive OOM'd dispatches
        self._spec_warm = False      # first spec round compiles
        # finished requests kept for result retrieval — bounded so a
        # long-lived replica's host memory doesn't grow with total
        # traffic (generate() releases its own as it returns)
        self._keep_finished = 256
        # -- resilience state (ISSUE 13) ------------------------------
        # stamped at every COMPLETED dispatch: the router's health
        # signal (a wedged dispatch stops the clock; an idle engine's
        # stale beat is fine — health checks gate on has_unfinished)
        self.heartbeat = time.monotonic()
        # fenced = this engine's requests were exported elsewhere; a
        # zombie thread waking from a wedge must not keep serving
        self._fenced = False
        # emergency drain-and-export landing zone (incident hook)
        self.emergency_exports = None
        self._incident_armed = False
        # live introspection (/tracez): register this engine's trace
        # spool weakly — a collected engine simply drops off the
        # page; total fallback because a debug surface must never
        # fail engine construction
        try:
            from ...monitor import server as _mserver

            _mserver.add_trace_source(self.export_traces)
        except Exception:
            pass

    # -- request intake ----------------------------------------------
    def add_request(self, prompt_ids, sampling=None, on_token=None,
                    req_id=None):
        """Queue one request; returns its id. `on_token(req, token)`
        streams every generated token as its dispatch completes. A
        FENCED engine refuses intake — its step() no-ops, so a
        queued request would silently strand forever."""
        self._check_fenced()
        req = Request(prompt_ids, sampling=sampling,
                      on_token=on_token, req_id=req_id)
        self.scheduler.add(req)
        self._requests[req.req_id] = req
        self._prune_finished()
        _cmon.stat_add("serve/requests", 1)
        return req.req_id

    def _prune_finished(self):
        """Cap retained FINISHED/ABORTED requests at
        `_keep_finished` (oldest dropped first) — results live until
        read or displaced, never forever."""
        done = [rid for rid, r in self._requests.items()
                if r.finished]
        for rid in done[:max(0, len(done) - self._keep_finished)]:
            # finished entries only: their blocks were released by
            # scheduler.finish/abort before they ever became prunable
            del self._requests[rid]  # noqa: PTA072

    def release_request(self, req_id):
        """Drop a finished request's retained record (results
        consumed). Live requests must be aborted first."""
        req = self._requests.get(req_id)
        if req is not None and req.finished:
            # finished-only guard above: blocks already released
            del self._requests[req_id]  # noqa: PTA072

    def abort_request(self, req_id):
        req = self._requests.get(req_id)
        if req is not None and not req.finished:
            self.scheduler.abort(req)

    def get_request(self, req_id):
        return self._requests[req_id]

    def has_unfinished(self):
        return self.scheduler.has_work()

    # -- the engine loop ---------------------------------------------
    def step(self):
        """One engine iteration: admissions (each prefilled, its
        first token emitted) + one decode dispatch for the running
        batch. Returns {req_id: token} emitted this step. A fenced
        engine (requests exported after a wedge/failover) no-ops —
        its tokens would double-serve requests replaying elsewhere."""
        if self._fenced:
            return {}
        emitted = {}

        def _on_admit(req):
            # prefill AS each request admits — a fault later in the
            # same admission pass can't strand an admitted request
            # with never-written K/V
            self._emit(req, self._prefill(req), emitted)

        admitted = self.scheduler.schedule(on_admit=_on_admit)
        if not admitted and not self.scheduler.running \
                and self.scheduler.waiting:
            # an idle engine that can't admit its queue head will
            # never make progress — a pool sized below one request's
            # footprint must be LOUD, not a silent spin
            head = self.scheduler.waiting[0]
            need = self.cache.blocks_for_tokens(head.context_len) \
                + self.scheduler._lookahead
            if need > self.cache.num_blocks - 1:
                raise RuntimeError(
                    f"KV pool too small: {head.req_id} needs {need} "
                    f"block(s) but the pool has only "
                    f"{self.cache.num_blocks - 1} usable — raise "
                    "PADDLE_SERVE_POOL_BYTES or num_blocks")
        if self.scheduler.running:
            self._decode_batch(emitted)
        return emitted

    def generate(self, prompts, sampling=None, timeout_s=None):
        """Submit `prompts` (lists of token ids) and run the engine
        to drain; returns each prompt's generated ids, in order.

        `timeout_s` bounds the WHOLE drain: when it elapses with work
        still live, raises `EngineTimeout` carrying
        `state_summary()` instead of looping forever (a queue the
        pool can't serve, a steady stream of evict/readmit churn).
        The bound is judged between dispatches — a dispatch wedged
        INSIDE XLA is the watchdog's jurisdiction (see
        `arm_incident_export`).

        A request that EXPIRES (deadline_s) returns its partial —
        for a never-admitted request, empty — output list in place:
        deadline misses are a normal outcome under SLO load, counted
        under serve/deadline_aborts. Callers that must distinguish
        expiry per request should use add_request() + get_request()
        and read `state`."""
        ids = [self.add_request(p, sampling=sampling)
               for p in prompts]
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        while self.has_unfinished() and not self._fenced:
            self.step()
            if deadline is not None and self.has_unfinished() \
                    and time.monotonic() > deadline:
                raise EngineTimeout(
                    f"generate() exceeded timeout_s={timeout_s} "
                    f"with {len(self.scheduler.running)} running / "
                    f"{len(self.scheduler.waiting)} waiting",
                    engine_state=self.state_summary())
        exported = [i for i in ids
                    if self._requests[i].state == EXPORTED]
        if exported:
            # an incident hook fenced this engine mid-generate and
            # exported the work — partial outputs must not read as
            # completed generations
            raise EngineTimeout(
                f"engine fenced mid-generate: {len(exported)} "
                "request(s) were emergency-exported (see "
                "emergency_exports) — replay them on a healthy "
                "engine", engine_state=self.state_summary())
        outs = [self._requests[i].output_ids for i in ids]
        for i in ids:                # results consumed: release
            self.release_request(i)
        return outs

    # -- prefill -----------------------------------------------------
    def _prefill_fn(self, padded_len):
        import jax

        jfn = self._prefill_jits.get(padded_len)
        if jfn is None:
            cfg = self.config
            fn = functools.partial(
                _mr.prefill_step, n_head=cfg.num_heads,
                eps=cfg.layer_norm_eps, block_size=self.block_size)
            jfn = jax.jit(
                fn, donate_argnums=(3, 4) if self._donate else ())
            self._prefill_jits[padded_len] = jfn
        return jfn

    def _draft_prefill_fn(self, padded_len):
        import jax

        jfn = self._draft_prefill_jits.get(padded_len)
        if jfn is None:
            cfg = self.config
            fn = functools.partial(
                _mr.prefill_step, n_head=cfg.num_heads,
                eps=cfg.layer_norm_eps, block_size=self.block_size)
            jfn = jax.jit(
                fn, donate_argnums=(3, 4) if self._donate else ())
            self._draft_prefill_jits[padded_len] = jfn
        return jfn

    def _prefill(self, req):
        """Causal forward over the (re)admitted request's context —
        prompt plus any generation an eviction preserved — writing
        its K/V and sampling the next token. With prefix caching on
        and a cache hit at admission, only the uncached TAIL runs
        (`_prefill_tail`); either way the request's full immutable
        blocks are published for later sharers, and with speculation
        armed the draft model prefills its twin pools over the same
        table."""
        import jax.numpy as jnp

        ctx = req.prompt_ids + req.output_ids
        plen = len(ctx)
        if self.prefix_cache and req.cached_tokens:
            return self._prefill_tail(req, ctx, plen)
        padded = self.cache.blocks_for_tokens(plen) * self.block_size
        ids = np.zeros((1, padded), np.int32)
        ids[0, :plen] = ctx
        table = self.cache.block_table(req.req_id,
                                       self.max_blocks_per_seq)
        s = req.sampling
        # a fresh bucket's first dispatch runs the lazy XLA compile —
        # keep that sample out of the dispatch histogram (it would
        # poison the p99), but still count it in serve/prefill_us
        fresh_bucket = padded not in self._prefill_jits
        t0 = time.perf_counter()
        with _flight.in_flight("serve_prefill", req.req_id,
                               tokens=plen):
            tok, self.cache.k, self.cache.v = self._prefill_fn(padded)(
                self.params, jnp.asarray(ids), np.int32(plen),
                self.cache.k, self.cache.v, jnp.asarray(table),
                np.float32(s.temperature), np.int32(s.top_k),
                np.uint32(_mr.seed_for(s.seed, plen)))
            tok = int(tok)
            if self._draft_params is not None:
                _, self.cache.k_draft, self.cache.v_draft = \
                    self._draft_prefill_fn(padded)(
                        self._draft_params, jnp.asarray(ids),
                        np.int32(plen), self.cache.k_draft,
                        self.cache.v_draft, jnp.asarray(table),
                        np.float32(0.0), np.int32(0), np.uint32(0))
                req._spec_gap = False
        dur_us = int((time.perf_counter() - t0) * 1e6)
        _cmon.stat_add("serve/prefill_us", dur_us)
        if not fresh_bucket and _perf.dispatch_timing_enabled():
            # `int(tok)` above already blocked on the dispatch —
            # this wall time is device time, not the enqueue
            _perf.observe_dispatch(self._prefill_label, dur_us)
        if padded not in self._prefill_captured:
            self._prefill_captured[padded] = len(self._prefill_captured)
            self._capture_prefill_cost(padded, ids, plen, table, s)
        if _trace._armed:
            # replayed > 0 marks an eviction-recompute or a failover/
            # drain replay leg (the preserved output_ids re-prefill)
            _trace.note(req, "prefill", tokens=plen, dur_us=dur_us,
                        replayed=len(req.output_ids))
        self.cache.register_prefix(req.req_id, ctx)
        self.heartbeat = time.monotonic()
        return tok

    def _tail_fn(self, t_pad, draft):
        import jax

        jits = self._draft_tail_jits if draft else self._tail_jits
        jfn = jits.get(t_pad)
        if jfn is None:
            cfg = self.config
            fn = functools.partial(
                _mr.prefill_tail_step, n_head=cfg.num_heads,
                eps=cfg.layer_norm_eps, block_size=self.block_size)
            jfn = jax.jit(
                fn, donate_argnums=(4, 5) if self._donate else ())
            jits[t_pad] = jfn
        return jfn

    def _prefill_tail(self, req, ctx, plen):
        """Prefix-cache hit: the leading `req.cached_tokens` (a block
        multiple, capped below plen) already sit in shared blocks —
        compile/dispatch over the TAIL only. The tail writes land
        exclusively in the request's private blocks (admission caps
        sharing below the full context, so the tail is never empty);
        with the serving sanitizer armed, `check_cow` proves it."""
        import jax.numpy as jnp

        cached = req.cached_tokens
        tail = ctx[cached:]
        t_pad = (self.cache.blocks_for_tokens(plen) * self.block_size
                 - cached)
        ids = np.zeros((1, t_pad), np.int32)
        ids[0, :len(tail)] = tail
        table = self.cache.block_table(req.req_id,
                                       self.max_blocks_per_seq)
        if getattr(_san, "_serving", False):
            private = self.cache.allocator.owned(
                req.req_id)[cached // self.block_size:]
            for bid in private:
                self.cache.allocator.check_cow(bid)
        s = req.sampling
        t0 = time.perf_counter()
        with _flight.in_flight("serve_prefill", req.req_id,
                               tokens=len(tail), cached=cached):
            tok, self.cache.k, self.cache.v = \
                self._tail_fn(t_pad, draft=False)(
                    self.params, jnp.asarray(ids), np.int32(cached),
                    np.int32(plen), self.cache.k, self.cache.v,
                    jnp.asarray(table), np.float32(s.temperature),
                    np.int32(s.top_k),
                    np.uint32(_mr.seed_for(s.seed, plen)))
            tok = int(tok)
            if self._draft_params is not None:
                _, self.cache.k_draft, self.cache.v_draft = \
                    self._tail_fn(t_pad, draft=True)(
                        self._draft_params, jnp.asarray(ids),
                        np.int32(cached), np.int32(plen),
                        self.cache.k_draft, self.cache.v_draft,
                        jnp.asarray(table), np.float32(0.0),
                        np.int32(0), np.uint32(0))
                req._spec_gap = False
        dur_us = int((time.perf_counter() - t0) * 1e6)
        _cmon.stat_add("serve/prefill_us", dur_us)
        _cmon.stat_add("serve/prefix/prefill_tokens_saved", cached)
        if _trace._armed:
            _trace.note(req, "prefill", tokens=len(tail),
                        cached=cached, dur_us=dur_us,
                        replayed=len(req.output_ids))
        self.cache.register_prefix(req.req_id, ctx)
        self.heartbeat = time.monotonic()
        return tok

    def _capture_prefill_cost(self, padded, ids, plen, table, s):
        """Roofline-ledger capture for one prefill bucket: an AOT
        lower+compile over the just-dispatched shapes (the NEW pools
        stand in for the donated-away ones — same avals), then
        `perf/program/serve_prefill:<Model>[#n]/*`. One extra backend
        compile per bucket, first dispatch only — the jit capture
        discipline; PADDLE_PERF_PROGRAM=0 opts out. Never raises."""
        import jax.numpy as jnp

        if not _perf.program_capture_enabled():
            return
        try:
            n = self._prefill_captured[padded]
            name = (self._prefill_label if n == 0
                    else f"{self._prefill_label}#{n}")
            with _flight.in_flight("perf_capture", name):
                compiled = self._prefill_fn(padded).lower(
                    self.params, jnp.asarray(ids), np.int32(plen),
                    self.cache.k, self.cache.v, jnp.asarray(table),
                    np.float32(s.temperature), np.int32(s.top_k),
                    np.uint32(0)).compile()
            _perf.record_program_cost(name, compiled)
        except Exception:
            pass  # the ledger is observability, never a serving error

    # -- decode ------------------------------------------------------
    def _batch_arrays(self):
        """Fixed-shape [max_batch] dispatch inputs; inactive slots
        decode garbage against the NULL block and are dropped on the
        host side."""
        b = self.max_batch
        ids = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        tables = np.full((b, self.max_blocks_per_seq), NULL_BLOCK,
                         np.int32)
        lens = np.ones((b,), np.int32)
        temp = np.zeros((b,), np.float32)
        topk = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        for slot, req in self.scheduler.running.items():
            ctx = req.prompt_ids + req.output_ids
            ids[slot] = ctx[-1]
            pos[slot] = len(ctx) - 1
            tables[slot] = self.cache.block_table(
                req.req_id, self.max_blocks_per_seq)
            lens[slot] = len(ctx)
            s = req.sampling
            temp[slot] = s.temperature
            topk[slot] = s.top_k
            seeds[slot] = _mr.seed_for(s.seed, len(ctx))
        return ids, pos, tables, lens, temp, topk, seeds

    def _dispatch_decode(self, arrays):
        import jax.numpy as jnp

        ids, pos, tables, lens, temp, topk, seeds = arrays
        args = (self.params, jnp.asarray(ids), jnp.asarray(pos),
                self.cache.k, self.cache.v, jnp.asarray(tables),
                jnp.asarray(lens), jnp.asarray(temp),
                jnp.asarray(topk), jnp.asarray(seeds))
        if self._decode_exe is None:
            self._load_persistent(args)
        fn = self._decode_exe or self._decode_jit
        try:
            toks, self.cache.k, self.cache.v = fn(*args)
        except TypeError:
            if fn is not self._decode_jit:   # stale cached executable
                self._decode_exe = self._decode_jit
                toks, self.cache.k, self.cache.v = \
                    self._decode_jit(*args)
            else:
                raise
        return np.asarray(toks)

    def _load_persistent(self, args):
        """First decode dispatch: route the compile through the PR-8
        persistent cache so a serving replica restart is a warm hit.
        Best effort — any trouble keeps the plain jitted step."""
        from ...jit import persistent_cache as _pcache

        self._decode_exe = self._decode_jit
        if not _pcache.enabled():
            self._capture_decode_cost(args)
            return
        try:
            lowered = self._decode_jit.lower(*args)
            compiled, outcome = _pcache.load_or_compile(
                lowered, self._pcache_label)
            if outcome != "off":
                self._decode_exe = compiled
                # pcache just handed us the compiled executable —
                # the ledger capture is free here
                self._capture_decode_cost(args, compiled=compiled)
            else:
                self._capture_decode_cost(args)
        except Exception:
            self._decode_exe = self._decode_jit
            self._capture_decode_cost(args)

    def _capture_decode_cost(self, args, compiled=None):
        """Roofline-ledger capture for the decode program
        (`perf/program/serve_decode:<Model>/*`). Reuses the
        persistent-cache executable when one exists; otherwise one
        extra AOT backend compile at first dispatch —
        PADDLE_PERF_PROGRAM=0 opts out. Never raises."""
        if not _perf.program_capture_enabled():
            return
        try:
            if compiled is None:
                with _flight.in_flight("perf_capture",
                                       self._pcache_label):
                    compiled = self._decode_jit.lower(*args).compile()
            _perf.record_program_cost(self._pcache_label, compiled)
        except Exception:
            pass  # the ledger is observability, never a serving error

    def _pools_deleted(self):
        """Did a failed DONATING dispatch consume the pools? (A real
        RESOURCE_EXHAUSTED mid-execution deletes donated buffers —
        retrying with them is the PTA041 use-after-donate crash.)"""
        try:
            dead = bool(self.cache.k.is_deleted()
                        or self.cache.v.is_deleted())
            if not dead and self.cache.k_draft is not None:
                dead = bool(self.cache.k_draft.is_deleted()
                            or self.cache.v_draft.is_deleted())
            return dead
        except Exception:
            return False

    def _decode_batch(self, emitted):
        """Grow tables, dispatch once, apply stop conditions. An OOM
        (real or chaos-injected) evicts the youngest request and
        retries with the smaller batch; if the failed dispatch
        consumed the DONATED pools, rebuild them and replay every
        running request (position-keyed sampling makes the replay
        token-exact). A persistent OOM re-raises after
        max(3, max_batch) consecutive failed dispatches instead of
        spinning on evict/readmit forever."""
        if self.spec_k > 1:
            return self._spec_decode_batch(emitted)
        # snapshot the batch, but re-check membership per request:
        # growing request A can evict request B later in the
        # snapshot, and growing an evicted B would strand blocks on
        # a request the dispatch no longer covers
        for req in list(self.scheduler.running.values()):
            self.scheduler.ensure_capacity(req, new_tokens=1)
        if not self.scheduler.running:
            return
        arrays = self._batch_arrays()
        # first decode dispatch compiles (and runs _load_persistent)
        # — keep it out of the dispatch histogram like prefill
        fresh_decode = self._decode_exe is None
        t0 = time.perf_counter()
        try:
            with _flight.in_flight("serve_decode", "decode",
                                   batch=len(self.scheduler.running)):
                if _chaos._armed:
                    _chaos.hit("serve_decode",
                               batch=len(self.scheduler.running))
                toks = self._dispatch_decode(arrays)
        except Exception as e:
            from ...monitor import memory as _memory

            if not _memory.is_oom_error(e):
                raise
            self._oom_streak += 1
            if self._oom_streak > max(3, self.max_batch):
                raise
            _cmon.stat_add("serve/oom_evictions", 1)
            if self._pools_deleted():
                _cmon.stat_add("serve/pool_resets", 1)
                _flight.record("serve_pool_reset",
                               batch=len(self.scheduler.running))
                for req in list(self.scheduler.running.values()):
                    self.scheduler.evict(req)
                self.cache.reset_pools()
                return                # next step() re-prefills
            victim = self.scheduler._pick_victim()
            if victim is None:
                raise
            self.scheduler.evict(victim)
            return self._decode_batch(emitted)
        self._oom_streak = 0
        self.heartbeat = time.monotonic()
        decode_us = int((time.perf_counter() - t0) * 1e6)
        _cmon.stat_add("serve/decode_us", decode_us)
        if not fresh_decode and _perf.dispatch_timing_enabled():
            # _dispatch_decode's np.asarray(toks) already blocked —
            # measured device time for the roofline, like prefill
            _perf.observe_dispatch(self._pcache_label, decode_us)
        for slot, req in list(self.scheduler.running.items()):
            self._emit(req, int(toks[slot]), emitted)

    # -- speculative decode (spec_k > 1) -----------------------------
    def _wide_tables(self, tables):
        """Spec dispatch tables carry ONE extra guaranteed-NULL
        column: a near-`max_seq_len` slot whose position overflows
        the real table width clamps into the null block (XLA gather
        clamps out-of-range indices) instead of corrupting an at-cap
        sequence's own last block."""
        wide = np.full(
            (tables.shape[0], self.max_blocks_per_seq + 1),
            NULL_BLOCK, np.int32)
        wide[:, :-1] = tables
        return wide

    def _check_spec_cow(self, running):
        """PTA074 runtime half (armed only): every block a spec round
        writes through — the realign/pending position onward — must
        be exclusively owned. Shared prefix blocks all precede the
        write frontier, so a trip here is a refcount/COW bug, not
        load."""
        if not getattr(_san, "_serving", False):
            return
        for req in running.values():
            lo = req.context_len - (2 if req._spec_gap else 1)
            for bid in self.cache.allocator.owned(
                    req.req_id)[lo // self.block_size:]:
                self.cache.allocator.check_cow(bid)

    def _draft_propose(self, running, wide_j):
        """k batched draft-model decode dispatches -> k-1 proposed
        tokens per running request.

        Step 0 is the REALIGN step: a request whose previous round
        accepted every proposal has one context position whose draft
        KV was never written (the verify step only writes TARGET KV).
        Re-feeding ctx[-2] at its own position rewrites that slot
        idempotently; requests without the gap re-feed ctx[-1]
        (duplicating step 1's write — same value, discarded output),
        keeping the dispatch fixed-shape. Steps 1..k-1 feed the
        pending token then each proposal onward, every write landing
        in the request's private tail — position-keyed seeds make
        the proposals deterministic across replays."""
        import jax.numpy as jnp

        b = self.max_batch
        r_ids = np.zeros((b,), np.int32)
        r_pos = np.zeros((b,), np.int32)
        r_lens = np.ones((b,), np.int32)
        zeros_f = np.zeros((b,), np.float32)
        zeros_i = np.zeros((b,), np.int32)
        zeros_u = np.zeros((b,), np.uint32)
        for slot, req in running.items():
            ctx = req.prompt_ids + req.output_ids
            back = 2 if req._spec_gap else 1
            r_ids[slot] = ctx[-back]
            r_pos[slot] = len(ctx) - back
            r_lens[slot] = len(ctx) - back + 1
        _, self.cache.k_draft, self.cache.v_draft = self._draft_jit(
            self._draft_params, jnp.asarray(r_ids),
            jnp.asarray(r_pos), self.cache.k_draft,
            self.cache.v_draft, wide_j, jnp.asarray(r_lens),
            jnp.asarray(zeros_f), jnp.asarray(zeros_i),
            jnp.asarray(zeros_u))
        drafts = {slot: [] for slot in running}
        ids = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        lens = np.ones((b,), np.int32)
        temp = np.zeros((b,), np.float32)
        topk = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        for slot, req in running.items():
            ctx = req.prompt_ids + req.output_ids
            ids[slot] = ctx[-1]
            pos[slot] = len(ctx) - 1
            lens[slot] = len(ctx)
            s = req.sampling
            temp[slot] = s.temperature
            topk[slot] = s.top_k
            seeds[slot] = _mr.seed_for(s.seed, len(ctx))
        for _ in range(self.spec_k - 1):
            toks, self.cache.k_draft, self.cache.v_draft = \
                self._draft_jit(
                    self._draft_params, jnp.asarray(ids),
                    jnp.asarray(pos), self.cache.k_draft,
                    self.cache.v_draft, wide_j, jnp.asarray(lens),
                    jnp.asarray(temp), jnp.asarray(topk),
                    jnp.asarray(seeds))
            toks = np.asarray(toks)
            for slot, req in running.items():
                d = int(toks[slot])
                drafts[slot].append(d)
                ids[slot] = d
                pos[slot] += 1
                lens[slot] += 1
                seeds[slot] = _mr.seed_for(req.sampling.seed,
                                           int(lens[slot]))
        return drafts

    def _dispatch_verify(self, running, drafts, wide_j, arrays):
        """ONE fixed-shape target dispatch over all k slots: slot 0
        the pending token, slots 1.. the draft proposals. Returns
        [B, k] target choices, each sampled with the SAME
        position-keyed seed the k=1 engine would use."""
        import jax.numpy as jnp

        _, pos, _, lens, temp, topk, _ = arrays
        b = self.max_batch
        k = self.spec_k
        v_ids = np.zeros((b, k), np.int32)
        v_seeds = np.zeros((b, k), np.uint32)
        for slot, req in running.items():
            ctx = req.prompt_ids + req.output_ids
            v_ids[slot, 0] = ctx[-1]
            for t, d in enumerate(drafts[slot]):
                v_ids[slot, t + 1] = d
            for t in range(k):
                v_seeds[slot, t] = _mr.seed_for(req.sampling.seed,
                                                len(ctx) + t)
        toks, self.cache.k, self.cache.v = self._verify_jit(
            self.params, jnp.asarray(v_ids), jnp.asarray(pos),
            self.cache.k, self.cache.v, wide_j, jnp.asarray(lens),
            jnp.asarray(temp), jnp.asarray(topk),
            jnp.asarray(v_seeds))
        return np.asarray(toks)

    def _spec_decode_batch(self, emitted):
        """One speculative round: k draft dispatches propose, one
        verify dispatch checks all proposals, the engine emits the
        longest agreeing prefix plus the first corrected token —
        1..k tokens per round, all of them the target's own
        position-seeded choices (token-identical to k=1). OOM
        handling mirrors `_decode_batch`: evict-and-retry, or
        rebuild-and-replay when a donating dispatch consumed the
        pools."""
        import jax.numpy as jnp

        k = self.spec_k
        for req in list(self.scheduler.running.values()):
            # k-aware growth, capped so an almost-finished sequence
            # never asks for blocks past max_seq_len's table width
            self.scheduler.ensure_capacity(req, new_tokens=min(
                k, max(1, self.max_seq_len - req.context_len)))
        if not self.scheduler.running:
            return
        arrays = self._batch_arrays()
        wide_j = jnp.asarray(self._wide_tables(arrays[2]))
        running = dict(self.scheduler.running)
        self._check_spec_cow(running)
        fresh_decode = self._verify_jit is not None \
            and not getattr(self, "_spec_warm", False)
        t0 = time.perf_counter()
        try:
            with _flight.in_flight("serve_decode", "spec_decode",
                                   batch=len(running), k=k):
                if _chaos._armed:
                    _chaos.hit("serve_decode", batch=len(running))
                drafts = self._draft_propose(running, wide_j)
                if _chaos._armed:
                    rule = _chaos.hit("serve_spec_verify",
                                      batch=len(running), k=k)
                    if rule is not None:
                        # forced draft divergence: verification must
                        # reject every corrupted proposal and still
                        # emit the target's own token — degrading to
                        # >= 1 token/round, never to wrong tokens
                        vocab = self.config.vocab_size
                        drafts = {
                            slot: [(d + 1) % vocab for d in ds]
                            for slot, ds in drafts.items()}
                toks = self._dispatch_verify(running, drafts,
                                             wide_j, arrays)
        except Exception as e:
            from ...monitor import memory as _memory

            if not _memory.is_oom_error(e):
                raise
            self._oom_streak += 1
            if self._oom_streak > max(3, self.max_batch):
                raise
            _cmon.stat_add("serve/oom_evictions", 1)
            if self._pools_deleted():
                _cmon.stat_add("serve/pool_resets", 1)
                _flight.record("serve_pool_reset",
                               batch=len(self.scheduler.running))
                for req in list(self.scheduler.running.values()):
                    self.scheduler.evict(req)
                self.cache.reset_pools()
                return                # next step() re-prefills
            victim = self.scheduler._pick_victim()
            if victim is None:
                raise
            self.scheduler.evict(victim)
            return self._spec_decode_batch(emitted)
        self._oom_streak = 0
        self._spec_warm = True
        self.heartbeat = time.monotonic()
        decode_us = int((time.perf_counter() - t0) * 1e6)
        _cmon.stat_add("serve/decode_us", decode_us)
        if not fresh_decode and _perf.dispatch_timing_enabled():
            _perf.observe_dispatch(self._pcache_label, decode_us)
        for slot, req in sorted(running.items()):
            ds = drafts[slot]
            row = toks[slot]
            m = 0
            while m < len(ds) and ds[m] == int(row[m]):
                m += 1
            _cmon.stat_add("serve/spec/proposed", len(ds))
            _cmon.stat_add("serve/spec/accepted", m)
            _cmon.hist_observe("serve/hist/accept_len", m + 1)
            # all proposals accepted -> one draft-KV position was
            # never written (verify writes only TARGET KV); the next
            # round's realign step fills it
            req._spec_gap = (m == len(ds))
            for t in range(m + 1):
                self._emit(req, int(row[t]), emitted)
                if req.finished:
                    break

    # -- token emission / stop conditions ----------------------------
    def _emit(self, req, token, emitted):
        now = time.perf_counter()
        req.output_ids.append(token)
        req.token_times.append(now)
        emitted[req.req_id] = token
        _cmon.stat_add("serve/tokens", 1)
        # latency distributions off the token_times stream (ISSUE
        # 15): first token -> TTFT from this engine leg's arrival;
        # later tokens -> the inter-token gap a streaming client sees
        if len(req.token_times) == 1:
            _cmon.hist_observe("serve/hist/ttft_us",
                               (now - req.arrival_perf) * 1e6)
        else:
            _cmon.hist_observe(
                "serve/hist/itl_us",
                (now - req.token_times[-2]) * 1e6)
        if _trace._armed:
            _trace.note(req, "decode", n=len(req.output_ids))
        if req.on_token is not None:
            try:
                req.on_token(req.req_id, token)
            except Exception:
                _cmon.stat_add("serve/callback_errors", 1)
        s = req.sampling
        done = (req.stop_hit(token)
                or len(req.output_ids) >= s.max_new_tokens
                or req.context_len >= self.max_seq_len)
        if done:
            self.scheduler.finish(req, state=FINISHED)

    # -- lifecycle: drain / export / failover (ISSUE 13) -------------
    @property
    def fenced(self):
        return self._fenced

    def _check_fenced(self):
        if self._fenced:
            raise EngineOverloaded(
                "engine is fenced (its requests were exported after "
                "a wedge/failover) and will never serve again — "
                "route to another replica or build a fresh "
                "LLMEngine", engine_state=self.state_summary())

    def heartbeat_age(self, now=None):
        """Seconds since the last completed dispatch — the router's
        wedge signal (meaningful only while the engine has work)."""
        return (time.monotonic() if now is None else now) \
            - self.heartbeat

    def load_score(self):
        """Free KV blocks NET of queued-but-not-yet-admitted demand
        (prompt blocks + one decode lookahead per waiting request) —
        the router's least-loaded signal. Counting the queue makes
        back-to-back routing decisions see load the worker thread
        hasn't admitted yet. list() snapshots the deque atomically
        (C-level copy) so a concurrent admission pass can't raise
        mutated-during-iteration under the router's read."""
        lookahead = self.scheduler._lookahead
        pending = sum(
            self.cache.blocks_for_tokens(r.context_len) + lookahead
            for r in list(self.scheduler.waiting))
        return self.cache.allocator.free_blocks - pending

    def state_summary(self):
        """Host-side snapshot of where serving stands — attached to
        EngineTimeout/shed errors and flight records so a refused or
        abandoned request names the engine state that refused it."""
        sched = self.scheduler
        return {
            "waiting": len(sched.waiting),
            "running": len(sched.running),
            "draining": sched.draining,
            "fenced": self._fenced,
            "queue_depth": len(sched.waiting),
            "free_blocks": self.cache.allocator.free_blocks,
            "used_blocks": self.cache.allocator.used_blocks,
            "oom_streak": self._oom_streak,
            "heartbeat_age_s": round(self.heartbeat_age(), 3),
            "spec_k": self.spec_k,
            "prefix_cache": self.prefix_cache,
        }

    def _export(self, req):
        """One request's replayable snapshot: everything another
        engine needs to continue it TOKEN-EXACTLY (the position-keyed
        sampling seeds make the remaining tokens a pure function of
        prompt + generated-so-far + sampling)."""
        return {
            "req_id": req.req_id,
            "prompt_ids": list(req.prompt_ids),
            "output_ids": list(req.output_ids),
            "sampling": req.sampling,
            "deadline": req.deadline,
            "evictions": req.evictions,
            # trace continuity (ISSUE 15): the importing engine keeps
            # the SAME trace_id and the timeline-so-far, so a
            # replayed request's story reads export -> import ->
            # replay in one place
            "trace_id": req.trace_id,
        }

    def export_requests(self, fence=True):
        """Snapshot + retire every live request (EXPORTED terminal
        state — blocks release NOW, so even a dead replica's
        allocator audits clean) and by default FENCE the engine so a
        zombie thread can't keep serving the originals. RUNNING
        requests export first (admission order — most progress
        resumes soonest), then the waiting queue in FIFO order.
        The exports MUST be re-added somewhere (`import_request`) or
        the requests are silently dropped — the PTA073 lint class."""
        if fence:
            self._fenced = True
        sched = self.scheduler
        running = sorted(
            sched.running.values(),
            key=lambda r: sched._admitted_at.get(r.req_id, -1))
        live = running + list(sched.waiting)
        exports = []
        for req in live:
            req.on_token = None   # zombie emits must not stream
            exp = self._export(req)
            sched.finish(req, state=EXPORTED)
            # snapshot the timeline AFTER finish so the export
            # carries its own "exported" terminal event
            exp["trace"] = list(req.trace)
            exports.append(exp)
        return exports

    def import_request(self, export, on_token=None, force=False):
        """Re-admit an exported request (failover/drain handoff):
        the preserved output_ids ride into the re-prefill exactly
        like an eviction's recompute-on-readmit, so generation
        continues where the exporting engine stopped. `force=True`
        (router failover) bypasses the drain gate and shed bound —
        the request already holds an admission promise. A fenced
        engine refuses even forced imports: it will never step."""
        self._check_fenced()
        req = Request(export["prompt_ids"],
                      sampling=export["sampling"],
                      on_token=on_token,
                      req_id=export["req_id"],
                      trace_id=export.get("trace_id"))
        req.output_ids = list(export["output_ids"])
        req.deadline = export.get("deadline")
        req.evictions = int(export.get("evictions", 0))
        if export.get("trace"):
            # continue the exporting engine's timeline (same
            # trace_id) — the ctor's fresh "add" event is replaced by
            # the full story plus this import leg
            req.trace = list(export["trace"])
        if _trace._armed:
            _trace.note(req, "import", replayed=len(req.output_ids),
                        forced=bool(force))
        self.scheduler.add(req, force=force)
        self._requests[req.req_id] = req
        return req.req_id

    def drain(self, timeout_s=None):
        """Graceful drain: stop admitting (new `add_request` sheds
        with EngineOverloaded), run RUNNING requests to completion,
        then export whatever is left — still-running requests that
        outlived `timeout_s` plus the whole waiting queue — for
        re-admission elsewhere. Returns the export list ([] when
        everything completed). The engine stays draining afterwards;
        `resume()` re-opens admission."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        with _flight.in_flight("serve_drain", "drain",
                               running=len(self.scheduler.running),
                               waiting=len(self.scheduler.waiting)):
            if _chaos._armed:
                _chaos.hit("serve_drain",
                           running=len(self.scheduler.running))
            self.scheduler.draining = True
            while self.scheduler.running and not self._fenced:
                if deadline is not None \
                        and time.monotonic() > deadline:
                    break
                self.step()
            exports = self.export_requests(fence=False)
            if self.emergency_exports:
                # the watchdog incident hook fenced this engine
                # MID-drain and already exported the in-flight work;
                # fold it into the return so the caller's "re-add
                # everything drain() returns" contract still covers
                # every request (returning [] here would read as
                # 'all completed' — the PTA073 drop class)
                exports = list(self.emergency_exports) + exports
                self.emergency_exports = None
        _cmon.stat_add("serve/drains", 1)
        _flight.record("serve_drain_done", exported=len(exports))
        return exports

    def resume(self):
        """Re-open admission after a drain (a replica rejoining the
        router pool). A FENCED engine cannot resume — its requests
        were exported and its pools may be mid-wedge; build a fresh
        engine instead."""
        if self._fenced:
            raise RuntimeError(
                "cannot resume a fenced engine — its requests were "
                "exported after a wedge/failover; create a fresh "
                "LLMEngine (the persistent compile cache makes that "
                "a warm start)")
        self.scheduler.draining = False

    # -- watchdog emergency drain-and-export -------------------------
    def arm_incident_export(self):
        """Register the PR-3/6 incident hook: when the watchdog dumps
        on a wedged dispatch (a stuck `serve_prefill`/`serve_decode`
        span), fence this engine and export its in-flight requests
        into `emergency_exports` — the autopsy bundle gains a
        REPLAYABLE workload instead of just a stack trace, and a
        router replays it on a healthy replica."""
        if not self._incident_armed:
            _flight.add_incident_hook(self._incident_export)
            self._incident_armed = True
        return self

    def disarm_incident_export(self):
        if self._incident_armed:
            _flight.remove_incident_hook(self._incident_export)
            self._incident_armed = False

    def _incident_export(self, reason):
        """Incident-hook body (best-effort by the PR-3 contract).
        Only a wedge with live work exports; an idle engine has
        nothing at stake. NO dispatches run here — the dispatch IS
        what wedged."""
        if self._fenced or not self.scheduler.has_work():
            return
        exports = self.export_requests(fence=True)
        self.emergency_exports = exports
        _cmon.stat_add("serve/drains", 1)
        _flight.record("serve_drain_done", exported=len(exports),
                       emergency=True, reason=str(reason))

    # -- trace spool (ISSUE 15) --------------------------------------
    def export_traces(self):
        """Trace spool (schema "paddle_tpu.trace/1") over every
        retained request's per-stage timeline — the input
        `python -m paddle_tpu.monitor trace` renders to a
        chrome-trace. Live requests show their story so far."""
        return _trace.export_requests(self._requests.values())

    def dump_traces(self, path):
        """Write export_traces() as JSON; returns the path."""
        import json

        with open(path, "w") as f:
            json.dump(self.export_traces(), f, default=str)
        return path

    # -- accounting --------------------------------------------------
    def check_drained(self):
        """Zero-leak audit after a drain: no live requests may remain
        and every KV block must be back on the free list. Returns the
        leak map ({} when clean) — with PADDLE_SANITIZE=serving armed
        each leak is also a PTA070 finding."""
        live = [r.req_id for r in self._requests.values()
                if not r.finished]
        leaks = self.cache.allocator.audit_leaks(live)
        return leaks
