"""LLMEngine — the TPU-native generation front end.

The user surface of the serving subsystem (ROADMAP item 1): a
GPTForCausalLM plus a paged KV cache, a continuous-batching
scheduler, and two compiled programs — per-bucket prefill and ONE
fixed-shape decode step covering all `max_batch` slots — that
together serve many concurrent mixed-length requests:

    engine = LLMEngine(model)
    engine.add_request([1, 2, 3], SamplingParams(max_new_tokens=8),
                       on_token=stream_cb)          # streaming
    outs = engine.generate([[1, 2, 3], [7, 8]])     # run-to-drain

Per engine `step()`: admit+prefill whatever the scheduler lets in,
grow block tables across block boundaries (evicting under pool
pressure), then ONE decode dispatch for the whole batch — inactive
slots ride along pointed at the NULL block. Stop conditions
(eos/stop ids/max_new_tokens/max_seq_len) apply host-side on the
returned tokens; finished requests free their blocks before the next
admission pass.

Compiled-step contract: the decode step is `jax.jit` with BOTH pools
DONATED (the engine re-adopts the returned pools each dispatch — the
PR-8/PR-9 donation discipline), and its first dispatch routes
through the persistent compile cache (`jit.persistent_cache`) under
the label `serve_decode:<Model>` — a serving replica restarting
against a warm PADDLE_SERVE-sized pool skips the backend compile
entirely (the ROADMAP cold-start story). Prefill compiles once per
block-rounded prompt-length bucket, so prompt-length cardinality is
`max_seq_len / block_size`, not `max_seq_len`.

Failure path: a RESOURCE_EXHAUSTED dispatch (real, or injected at
the `serve_decode` chaos site) evicts the youngest request and
retries — serving degrades to a smaller batch instead of dying.

Telemetry: `serve/{requests,tokens,prefill_us,decode_us,evictions,
queue_depth,kv_blocks/*}` counters plus `serve_prefill`/
`serve_decode` flight spans, all through the PR-1/PR-3 monitor hub.
"""
from __future__ import annotations

import functools
import math
import time

import numpy as np

from ...core import monitor as _cmon
from ...monitor import chaos as _chaos
from ...monitor import flight as _flight
from . import model_runner as _mr
from .kv_cache import NULL_BLOCK, PagedKVCache, env_max_batch
from .scheduler import (FINISHED, Request, SamplingParams,
                        Scheduler)

__all__ = ["LLMEngine"]


class LLMEngine:
    """Continuous-batching generation engine over one causal LM."""

    def __init__(self, model, max_batch=None, block_size=None,
                 num_blocks=None, pool_bytes=None, dtype=None,
                 static_batching=False, use_kernel=None,
                 donate=True):
        import jax

        self.params, self.config = _mr.extract_params(model)
        cfg = self.config
        self.max_batch = int(max_batch or env_max_batch())
        self.max_seq_len = int(cfg.max_seq_len)
        head_dim = cfg.hidden_size // cfg.num_heads
        self.cache = PagedKVCache(
            cfg.num_layers, cfg.num_heads, head_dim,
            block_size=block_size, num_blocks=num_blocks,
            pool_bytes=pool_bytes, dtype=dtype)
        self.block_size = self.cache.block_size
        # fixed table width: enough slots for a max-length sequence
        self.max_blocks_per_seq = math.ceil(
            self.max_seq_len / self.block_size)
        self.scheduler = Scheduler(self.cache, self.max_batch,
                                   self.max_seq_len,
                                   static_batching=static_batching)
        self._requests = {}          # req_id -> Request (all states)
        if use_kernel is None:
            from ...incubate.nn import pallas as _pl

            use_kernel = _pl.kernels_available() and \
                _pl.paged_attention.paged_decode_supported(
                    head_dim, self.block_size)
            self._kernel_interpret = _pl.interpret_mode()
        else:
            self._kernel_interpret = False
        self.use_kernel = bool(use_kernel)
        self._donate = bool(donate)

        decode = functools.partial(
            _mr.decode_step, n_head=cfg.num_heads,
            eps=cfg.layer_norm_eps, block_size=self.block_size,
            use_kernel=self.use_kernel,
            interpret=self._kernel_interpret)
        self._decode_jit = jax.jit(
            decode, donate_argnums=(3, 4) if self._donate else ())
        self._decode_exe = None      # persistent-cache hit, if any
        self._prefill_jits = {}      # padded len -> jitted prefill
        self._pcache_label = (
            f"serve_decode:{type(model).__name__}")
        self._oom_streak = 0         # consecutive OOM'd dispatches
        # finished requests kept for result retrieval — bounded so a
        # long-lived replica's host memory doesn't grow with total
        # traffic (generate() releases its own as it returns)
        self._keep_finished = 256

    # -- request intake ----------------------------------------------
    def add_request(self, prompt_ids, sampling=None, on_token=None,
                    req_id=None):
        """Queue one request; returns its id. `on_token(req, token)`
        streams every generated token as its dispatch completes."""
        req = Request(prompt_ids, sampling=sampling,
                      on_token=on_token, req_id=req_id)
        self.scheduler.add(req)
        self._requests[req.req_id] = req
        self._prune_finished()
        _cmon.stat_add("serve/requests", 1)
        return req.req_id

    def _prune_finished(self):
        """Cap retained FINISHED/ABORTED requests at
        `_keep_finished` (oldest dropped first) — results live until
        read or displaced, never forever."""
        done = [rid for rid, r in self._requests.items()
                if r.finished]
        for rid in done[:max(0, len(done) - self._keep_finished)]:
            # finished entries only: their blocks were released by
            # scheduler.finish/abort before they ever became prunable
            del self._requests[rid]  # noqa: PTA072

    def release_request(self, req_id):
        """Drop a finished request's retained record (results
        consumed). Live requests must be aborted first."""
        req = self._requests.get(req_id)
        if req is not None and req.finished:
            # finished-only guard above: blocks already released
            del self._requests[req_id]  # noqa: PTA072

    def abort_request(self, req_id):
        req = self._requests.get(req_id)
        if req is not None and not req.finished:
            self.scheduler.abort(req)

    def get_request(self, req_id):
        return self._requests[req_id]

    def has_unfinished(self):
        return self.scheduler.has_work()

    # -- the engine loop ---------------------------------------------
    def step(self):
        """One engine iteration: admissions (each prefilled, its
        first token emitted) + one decode dispatch for the running
        batch. Returns {req_id: token} emitted this step."""
        emitted = {}

        def _on_admit(req):
            # prefill AS each request admits — a fault later in the
            # same admission pass can't strand an admitted request
            # with never-written K/V
            self._emit(req, self._prefill(req), emitted)

        admitted = self.scheduler.schedule(on_admit=_on_admit)
        if not admitted and not self.scheduler.running \
                and self.scheduler.waiting:
            # an idle engine that can't admit its queue head will
            # never make progress — a pool sized below one request's
            # footprint must be LOUD, not a silent spin
            head = self.scheduler.waiting[0]
            need = self.cache.blocks_for_tokens(head.context_len) + 1
            if need > self.cache.num_blocks - 1:
                raise RuntimeError(
                    f"KV pool too small: {head.req_id} needs {need} "
                    f"block(s) but the pool has only "
                    f"{self.cache.num_blocks - 1} usable — raise "
                    "PADDLE_SERVE_POOL_BYTES or num_blocks")
        if self.scheduler.running:
            self._decode_batch(emitted)
        return emitted

    def generate(self, prompts, sampling=None):
        """Submit `prompts` (lists of token ids) and run the engine
        to drain; returns each prompt's generated ids, in order."""
        ids = [self.add_request(p, sampling=sampling)
               for p in prompts]
        while self.has_unfinished():
            self.step()
        outs = [self._requests[i].output_ids for i in ids]
        for i in ids:                # results consumed: release
            self.release_request(i)
        return outs

    # -- prefill -----------------------------------------------------
    def _prefill_fn(self, padded_len):
        import jax

        jfn = self._prefill_jits.get(padded_len)
        if jfn is None:
            cfg = self.config
            fn = functools.partial(
                _mr.prefill_step, n_head=cfg.num_heads,
                eps=cfg.layer_norm_eps, block_size=self.block_size)
            jfn = jax.jit(
                fn, donate_argnums=(3, 4) if self._donate else ())
            self._prefill_jits[padded_len] = jfn
        return jfn

    def _prefill(self, req):
        """Causal forward over the (re)admitted request's context —
        prompt plus any generation an eviction preserved — writing
        its K/V and sampling the next token."""
        import jax.numpy as jnp

        ctx = req.prompt_ids + req.output_ids
        plen = len(ctx)
        padded = self.cache.blocks_for_tokens(plen) * self.block_size
        ids = np.zeros((1, padded), np.int32)
        ids[0, :plen] = ctx
        table = self.cache.block_table(req.req_id,
                                       self.max_blocks_per_seq)
        s = req.sampling
        t0 = time.perf_counter()
        with _flight.in_flight("serve_prefill", req.req_id,
                               tokens=plen):
            tok, self.cache.k, self.cache.v = self._prefill_fn(padded)(
                self.params, jnp.asarray(ids), np.int32(plen),
                self.cache.k, self.cache.v, jnp.asarray(table),
                np.float32(s.temperature), np.int32(s.top_k),
                np.uint32(_mr.seed_for(s.seed, plen)))
            tok = int(tok)
        _cmon.stat_add("serve/prefill_us",
                       int((time.perf_counter() - t0) * 1e6))
        return tok

    # -- decode ------------------------------------------------------
    def _batch_arrays(self):
        """Fixed-shape [max_batch] dispatch inputs; inactive slots
        decode garbage against the NULL block and are dropped on the
        host side."""
        b = self.max_batch
        ids = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        tables = np.full((b, self.max_blocks_per_seq), NULL_BLOCK,
                         np.int32)
        lens = np.ones((b,), np.int32)
        temp = np.zeros((b,), np.float32)
        topk = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        for slot, req in self.scheduler.running.items():
            ctx = req.prompt_ids + req.output_ids
            ids[slot] = ctx[-1]
            pos[slot] = len(ctx) - 1
            tables[slot] = self.cache.block_table(
                req.req_id, self.max_blocks_per_seq)
            lens[slot] = len(ctx)
            s = req.sampling
            temp[slot] = s.temperature
            topk[slot] = s.top_k
            seeds[slot] = _mr.seed_for(s.seed, len(ctx))
        return ids, pos, tables, lens, temp, topk, seeds

    def _dispatch_decode(self, arrays):
        import jax.numpy as jnp

        ids, pos, tables, lens, temp, topk, seeds = arrays
        args = (self.params, jnp.asarray(ids), jnp.asarray(pos),
                self.cache.k, self.cache.v, jnp.asarray(tables),
                jnp.asarray(lens), jnp.asarray(temp),
                jnp.asarray(topk), jnp.asarray(seeds))
        if self._decode_exe is None:
            self._load_persistent(args)
        fn = self._decode_exe or self._decode_jit
        try:
            toks, self.cache.k, self.cache.v = fn(*args)
        except TypeError:
            if fn is not self._decode_jit:   # stale cached executable
                self._decode_exe = self._decode_jit
                toks, self.cache.k, self.cache.v = \
                    self._decode_jit(*args)
            else:
                raise
        return np.asarray(toks)

    def _load_persistent(self, args):
        """First decode dispatch: route the compile through the PR-8
        persistent cache so a serving replica restart is a warm hit.
        Best effort — any trouble keeps the plain jitted step."""
        from ...jit import persistent_cache as _pcache

        self._decode_exe = self._decode_jit
        if not _pcache.enabled():
            return
        try:
            lowered = self._decode_jit.lower(*args)
            compiled, outcome = _pcache.load_or_compile(
                lowered, self._pcache_label)
            if outcome != "off":
                self._decode_exe = compiled
        except Exception:
            self._decode_exe = self._decode_jit

    def _pools_deleted(self):
        """Did a failed DONATING dispatch consume the pools? (A real
        RESOURCE_EXHAUSTED mid-execution deletes donated buffers —
        retrying with them is the PTA041 use-after-donate crash.)"""
        try:
            return bool(self.cache.k.is_deleted()
                        or self.cache.v.is_deleted())
        except Exception:
            return False

    def _decode_batch(self, emitted):
        """Grow tables, dispatch once, apply stop conditions. An OOM
        (real or chaos-injected) evicts the youngest request and
        retries with the smaller batch; if the failed dispatch
        consumed the DONATED pools, rebuild them and replay every
        running request (position-keyed sampling makes the replay
        token-exact). A persistent OOM re-raises after
        max(3, max_batch) consecutive failed dispatches instead of
        spinning on evict/readmit forever."""
        # snapshot the batch, but re-check membership per request:
        # growing request A can evict request B later in the
        # snapshot, and growing an evicted B would strand blocks on
        # a request the dispatch no longer covers
        for req in list(self.scheduler.running.values()):
            self.scheduler.ensure_capacity(req)
        if not self.scheduler.running:
            return
        arrays = self._batch_arrays()
        t0 = time.perf_counter()
        try:
            with _flight.in_flight("serve_decode", "decode",
                                   batch=len(self.scheduler.running)):
                if _chaos._armed:
                    _chaos.hit("serve_decode",
                               batch=len(self.scheduler.running))
                toks = self._dispatch_decode(arrays)
        except Exception as e:
            from ...monitor import memory as _memory

            if not _memory.is_oom_error(e):
                raise
            self._oom_streak += 1
            if self._oom_streak > max(3, self.max_batch):
                raise
            _cmon.stat_add("serve/oom_evictions", 1)
            if self._pools_deleted():
                _cmon.stat_add("serve/pool_resets", 1)
                _flight.record("serve_pool_reset",
                               batch=len(self.scheduler.running))
                for req in list(self.scheduler.running.values()):
                    self.scheduler.evict(req)
                self.cache.reset_pools()
                return                # next step() re-prefills
            victim = self.scheduler._pick_victim()
            if victim is None:
                raise
            self.scheduler.evict(victim)
            return self._decode_batch(emitted)
        self._oom_streak = 0
        _cmon.stat_add("serve/decode_us",
                       int((time.perf_counter() - t0) * 1e6))
        for slot, req in list(self.scheduler.running.items()):
            self._emit(req, int(toks[slot]), emitted)

    # -- token emission / stop conditions ----------------------------
    def _emit(self, req, token, emitted):
        req.output_ids.append(token)
        req.token_times.append(time.perf_counter())
        emitted[req.req_id] = token
        _cmon.stat_add("serve/tokens", 1)
        if req.on_token is not None:
            try:
                req.on_token(req.req_id, token)
            except Exception:
                _cmon.stat_add("serve/callback_errors", 1)
        s = req.sampling
        done = (req.stop_hit(token)
                or len(req.output_ids) >= s.max_new_tokens
                or req.context_len >= self.max_seq_len)
        if done:
            self.scheduler.finish(req, state=FINISHED)

    # -- accounting --------------------------------------------------
    def check_drained(self):
        """Zero-leak audit after a drain: no live requests may remain
        and every KV block must be back on the free list. Returns the
        leak map ({} when clean) — with PADDLE_SANITIZE=serving armed
        each leak is also a PTA070 finding."""
        live = [r.req_id for r in self._requests.values()
                if not r.finished]
        leaks = self.cache.allocator.audit_leaks(live)
        return leaks
