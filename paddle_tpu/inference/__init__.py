"""paddle.inference — deployment API.

Parity target: paddle/fluid/inference/api/analysis_predictor.cc:160
(Config -> create_predictor -> zero-copy handles -> Run) and
paddle_infer python wrappers (python/paddle/inference/__init__.py).

TPU-native design: a saved model is a serialized StableHLO program
(jit.save) + params. create_predictor deserializes it and XLA compiles
for the target device — the analog of the analysis passes + engine
build; "zero-copy" handles wrap device arrays directly. The IR pass
pipeline (fusion/quant subgraphs) is subsumed by XLA's compiler.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "serving", "LLMEngine",
           "SamplingParams"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    TPU = 4
    GPU = 1  # accepted for compat; maps to the best local device


class Config:
    """reference: paddle/fluid/inference/api/paddle_analysis_config.h."""

    def __init__(self, prog_file=None, params_file=None):
        # accept either the jit.save prefix or the .pdmodel path
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._device = "tpu"
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._enable_memory_optim = True

    def set_prog_file(self, path):
        self.__init__(path)

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._prefix or "") + ".pdiparams"

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device, self._device_id = "tpu", device_id  # best device

    def enable_tpu(self, device_id=0):
        self._device, self._device_id = "tpu", device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes

    def set_cpu_math_library_num_threads(self, n):
        pass

    def set_optim_cache_dir(self, path):
        """AOT engine cache (reference: the serialized-TRT-engine cache
        dir): compiled XLA executables are serialized here keyed by
        input signature and reloaded by later processes, skipping
        recompilation. Like TRT engines, the blobs are locked to the
        runtime version + device type that produced them."""
        self._optim_cache_dir = path


class Tensor:
    """Zero-copy IO handle (reference: ZeroCopyTensor)."""

    def __init__(self, predictor, index, is_input):
        self._p = predictor
        self._i = index
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        assert self._is_input
        self._p._inputs[self._i] = np.asarray(arr)

    def copy_to_cpu(self):
        assert not self._is_input
        return np.asarray(self._p._outputs[self._i])

    def shape(self):
        v = (self._p._inputs[self._i] if self._is_input
             else self._p._outputs[self._i])
        return list(np.shape(v))


class Predictor:
    """reference: analysis_predictor.h:87 AnalysisPredictor."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load

        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._layer = jit_load(config._prefix)
        self._cache_dir = getattr(config, "_optim_cache_dir", None)
        if self._cache_dir is not None:
            import hashlib as _hl

            with open(config.prog_file(), "rb") as f:
                self._model_digest = _hl.sha256(f.read()).digest()
        self._aot = {}  # input-signature -> loaded executable
        n_in = len(self._layer._input_spec)
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs = [None] * len(self._input_names)
        self._outputs = []

    # -- AOT engine cache (serialized-TRT-engine analog) ------------------
    def _aot_call(self, avals):
        """Return a compiled executable for this input signature,
        loading from / saving to the optim cache dir."""
        import hashlib
        import os
        import pickle

        import jax

        sig = tuple((tuple(a.shape), str(a.dtype)) for a in avals)
        if sig in self._aot:
            return self._aot[sig]
        from jax.experimental import serialize_executable as se

        # key = model identity (the StableHLO bytes) + input signature,
        # so different models sharing one cache dir never collide
        h = hashlib.sha256()
        h.update(self._model_digest)
        h.update(repr(sig).encode())
        key = h.hexdigest()[:16]
        path = os.path.join(self._cache_dir, f"engine-{key}.pdexec")
        layer = self._layer
        # params/buffers are explicit executable ARGUMENTS — a closure
        # would hoist them into const_args, which serialize with a
        # device assignment that breaks on reload
        pkeys = sorted(layer._params)
        bkeys = sorted(layer._buffers)
        np_, nb = len(pkeys), len(bkeys)

        def fn(*all_args):
            pv = {k: v for k, v in zip(pkeys, all_args[:np_])}
            bv = {k: v for k, v in zip(bkeys, all_args[np_:np_ + nb])}
            return layer._exported.call(pv, bv, *all_args[np_ + nb:])

        # engines are single-device programs (the TRT-engine shape);
        # pin compile AND execution to device 0 so a multi-device test
        # env doesn't bake replication into the executable
        dev = jax.devices()[0]
        sds = jax.sharding.SingleDeviceSharding(dev)
        def param_vals():
            vals = [layer._params[k] for k in pkeys] + \
                [layer._buffers[k] for k in bkeys]
            return [v._value if hasattr(v, "_value") else v
                    for v in vals]

        if os.path.exists(path):
            with open(path, "rb") as f:
                blob, in_tree, out_tree = pickle.load(f)
            loaded = se.deserialize_and_load(blob, in_tree, out_tree,
                                             execution_devices=[dev])
        else:
            import jax.numpy as jnp

            specs = [jax.ShapeDtypeStruct(
                jnp.shape(v), jnp.asarray(v).dtype, sharding=sds)
                for v in param_vals()]
            specs += [jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=sds) for a in avals]
            compiled = jax.jit(fn).lower(*specs).compile()
            blob, in_tree, out_tree = se.serialize(compiled)
            os.makedirs(self._cache_dir, exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                pickle.dump((blob, in_tree, out_tree), f)
            os.replace(tmp, path)  # atomic: no torn engines
            loaded = se.deserialize_and_load(blob, in_tree, out_tree,
                                             execution_devices=[dev])

        placed_params = [jax.device_put(v, sds) for v in param_vals()]

        def exe(*xs):
            return loaded(*placed_params,
                          *[jax.device_put(x, sds) for x in xs])

        self._aot[sig] = exe
        return exe

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return Tensor(self, self._input_names.index(name), True)

    def run(self, inputs=None):
        import jax
        import jax.numpy as jnp

        if inputs is not None:
            self._inputs = [np.asarray(i) for i in inputs]
        if any(i is None for i in self._inputs):
            raise RuntimeError("not all inputs set (copy_from_cpu)")
        if self._cache_dir is not None:
            avals = [jnp.asarray(i) for i in self._inputs]
            exe = self._aot_call(avals)
            flat = jax.tree_util.tree_leaves(exe(*avals))
            self._outputs = list(flat)
            return [np.asarray(o) for o in self._outputs]
        out = self._layer(*self._inputs)
        flat = jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: hasattr(x, "_value"))
        self._outputs = [o._value if hasattr(o, "_value") else o
                         for o in flat]
        return [np.asarray(o) for o in self._outputs]

    def get_output_names(self):
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name):
        idx = int(name.replace("out", "") or 0)
        return Tensor(self, idx, False)

    def clone(self):
        import copy

        return copy.copy(self)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# the serving engine (LLMEngine / paged KV cache / continuous
# batching) pulls in jax + the model stack — keep it LAZY so the
# classic predictor surface stays import-light (PEP 562)
def __getattr__(name):
    if name == "serving":
        import importlib

        return importlib.import_module(".serving", __name__)
    if name in ("LLMEngine", "SamplingParams"):
        from . import serving

        return getattr(serving, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
