/* C inference API.
 *
 * Parity target: paddle/fluid/inference/capi_exp/pd_inference_api.h —
 * the C ABI for embedding Paddle inference in C/C++/Go/R programs
 * (Config -> Predictor -> Run with raw buffers).
 *
 * TPU-native implementation: the library embeds CPython and drives
 * paddle_tpu.inference (StableHLO deserialization + XLA compile); the
 * data plane is raw float32 buffers + int64 shape arrays across the C
 * boundary. Link with: -lpd_inference -lpython3.x
 *
 * The embedded interpreter honors PYTHONPATH (must include the
 * paddle_tpu checkout) and JAX_PLATFORMS (set "cpu" to force host
 * execution).
 */
#ifndef PD_INFERENCE_API_H_
#define PD_INFERENCE_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;

/* Global runtime (Py_Initialize). Returns 0 on success. */
int PD_Init(void);
void PD_Finalize(void);

/* Config (reference PD_ConfigCreate / PD_ConfigSetModel). `prefix` is
 * the jit.save / save_inference_model path prefix. */
PD_Config* PD_ConfigCreate(void);
void PD_ConfigSetModel(PD_Config* cfg, const char* prefix);
void PD_ConfigSetOptimCacheDir(PD_Config* cfg, const char* dir);
void PD_ConfigDestroy(PD_Config* cfg);

/* Predictor (reference PD_PredictorCreate / PD_PredictorRun). */
PD_Predictor* PD_PredictorCreate(PD_Config* cfg);
int PD_PredictorGetInputNum(PD_Predictor* pred);
void PD_PredictorDestroy(PD_Predictor* pred);

/* Run with float32 inputs; returns the first output.
 * in_data[i]: buffer for input i; in_shapes[i]: its dims;
 * in_ndims[i]: rank. On success (*out_data, *out_shape) are
 * malloc'd (free with PD_Free) and *out_ndim is set. Returns 0 on
 * success, nonzero on error (message via PD_GetLastError). */
int PD_PredictorRunFloat(PD_Predictor* pred,
                         const float* const* in_data,
                         const int64_t* const* in_shapes,
                         const int* in_ndims, int n_inputs,
                         float** out_data, int64_t** out_shape,
                         int* out_ndim);

const char* PD_GetLastError(void);
void PD_Free(void* p);

#ifdef __cplusplus
}
#endif

#endif /* PD_INFERENCE_API_H_ */
