"""C API build helper (reference: inference/capi_exp + goapi — the
C ABI other languages bind to; Go/R wrap exactly this kind of header).

`build_capi()` compiles libpd_inference.so from pd_inference_api.cc
(embedding CPython) through the cpp_extension toolchain and returns
its path; C programs include pd_inference_api.h and link against it
plus libpython."""
from __future__ import annotations

import os
import sysconfig

__all__ = ["build_capi", "header_path"]


def header_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "pd_inference_api.h")


def build_capi(verbose=False):
    """Compile the C API shared library; returns the .so path."""
    from ...utils.cpp_extension import load

    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "pd_inference_api.cc")
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_python_version()
    lib = load("pd_inference", [src],
               extra_cxx_flags=[f"-I{inc}", f"-I{here}"],
               extra_ldflags=[f"-L{libdir}", f"-lpython{ver}"],
               verbose=verbose)
    return lib._name
