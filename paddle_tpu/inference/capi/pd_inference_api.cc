// C inference API implementation — embeds CPython and drives
// paddle_tpu.inference (see pd_inference_api.h; reference:
// paddle/fluid/inference/capi_exp/pd_config.cc / pd_predictor.cc).
//
// Build (done by paddle_tpu.inference.capi.build_capi()):
//   g++ -O2 -fPIC -shared pd_inference_api.cc -o libpd_inference.so \
//       $(python3-config --includes) -lpython3.x

#include <Python.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>

#include "pd_inference_api.h"

namespace {

std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      g_last_error = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  } else {
    g_last_error = "unknown python error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* g_inference_mod = nullptr;

PyObject* inference_module() {
  if (g_inference_mod == nullptr) {
    g_inference_mod = PyImport_ImportModule("paddle_tpu.inference");
    if (g_inference_mod == nullptr) set_error_from_python();
  }
  return g_inference_mod;
}

}  // namespace

struct PD_Config {
  PyObject* obj;  // paddle_tpu.inference.Config
};

struct PD_Predictor {
  PyObject* obj;  // paddle_tpu.inference.Predictor
};

extern "C" {

int PD_Init(void) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
  }
  // Honor JAX_PLATFORMS even when a site hook pre-imported jax with a
  // different default (env alone is too late at that point — the
  // config route always works before first backend use).
  PyRun_SimpleString(
      "import os\n"
      "_p = os.environ.get('JAX_PLATFORMS')\n"
      "if _p:\n"
      "    import jax\n"
      "    jax.config.update('jax_platforms', _p.split(',')[0])\n");
  return inference_module() != nullptr ? 0 : 1;
}

void PD_Finalize(void) {
  g_inference_mod = nullptr;  // owned by the dying interpreter
  if (Py_IsInitialized()) Py_Finalize();
}

PD_Config* PD_ConfigCreate(void) {
  PyObject* mod = inference_module();
  if (mod == nullptr) return nullptr;
  PyObject* cfg = PyObject_CallMethod(mod, "Config", nullptr);
  if (cfg == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PD_Config* c = new PD_Config{cfg};
  return c;
}

void PD_ConfigSetModel(PD_Config* cfg, const char* prefix) {
  if (cfg == nullptr) return;
  PyObject* r =
      PyObject_CallMethod(cfg->obj, "set_prog_file", "s", prefix);
  if (r == nullptr)
    set_error_from_python();
  else
    Py_DECREF(r);
}

void PD_ConfigSetOptimCacheDir(PD_Config* cfg, const char* dir) {
  if (cfg == nullptr) return;
  PyObject* r =
      PyObject_CallMethod(cfg->obj, "set_optim_cache_dir", "s", dir);
  if (r == nullptr)
    set_error_from_python();
  else
    Py_DECREF(r);
}

void PD_ConfigDestroy(PD_Config* cfg) {
  if (cfg == nullptr) return;
  Py_XDECREF(cfg->obj);
  delete cfg;
}

PD_Predictor* PD_PredictorCreate(PD_Config* cfg) {
  PyObject* mod = inference_module();
  if (mod == nullptr || cfg == nullptr) return nullptr;
  PyObject* pred =
      PyObject_CallMethod(mod, "create_predictor", "O", cfg->obj);
  if (pred == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  return new PD_Predictor{pred};
}

int PD_PredictorGetInputNum(PD_Predictor* pred) {
  if (pred == nullptr) return -1;
  PyObject* names = PyObject_CallMethod(pred->obj, "get_input_names",
                                        nullptr);
  if (names == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyList_Size(names);
  Py_DECREF(names);
  return static_cast<int>(n);
}

void PD_PredictorDestroy(PD_Predictor* pred) {
  if (pred == nullptr) return;
  Py_XDECREF(pred->obj);
  delete pred;
}

int PD_PredictorRunFloat(PD_Predictor* pred, const float* const* in_data,
                         const int64_t* const* in_shapes,
                         const int* in_ndims, int n_inputs,
                         float** out_data, int64_t** out_shape,
                         int* out_ndim) {
  if (pred == nullptr) return 1;
  // marshal: numpy arrays via np.frombuffer(bytes).reshape(shape)
  PyObject* np = PyImport_ImportModule("numpy");
  if (np == nullptr) {
    set_error_from_python();
    return 1;
  }
  PyObject* inputs = PyList_New(n_inputs);
  for (int i = 0; i < n_inputs; ++i) {
    int64_t numel = 1;
    for (int d = 0; d < in_ndims[i]; ++d) numel *= in_shapes[i][d];
    PyObject* bytes = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(in_data[i]),
        numel * sizeof(float));
    PyObject* flat = PyObject_CallMethod(np, "frombuffer", "Os", bytes,
                                         "float32");
    Py_DECREF(bytes);
    if (flat == nullptr) {
      set_error_from_python();
      Py_DECREF(inputs);
      Py_DECREF(np);
      return 1;
    }
    PyObject* shape = PyTuple_New(in_ndims[i]);
    for (int d = 0; d < in_ndims[i]; ++d)
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(in_shapes[i][d]));
    PyObject* arr =
        PyObject_CallMethod(flat, "reshape", "O", shape);
    Py_DECREF(flat);
    Py_DECREF(shape);
    if (arr == nullptr) {
      set_error_from_python();
      Py_DECREF(inputs);
      Py_DECREF(np);
      return 1;
    }
    PyList_SET_ITEM(inputs, i, arr);  // steals
  }
  PyObject* outs = PyObject_CallMethod(pred->obj, "run", "O", inputs);
  Py_DECREF(inputs);
  if (outs == nullptr) {
    set_error_from_python();
    Py_DECREF(np);
    return 1;
  }
  PyObject* first = PySequence_GetItem(outs, 0);
  Py_DECREF(outs);
  if (first == nullptr) {
    set_error_from_python();
    Py_DECREF(np);
    return 1;
  }
  // out = np.ascontiguousarray(first, 'float32'); bytes = out.tobytes()
  PyObject* arr = PyObject_CallMethod(np, "ascontiguousarray", "Os",
                                      first, "float32");
  Py_DECREF(first);
  Py_DECREF(np);
  if (arr == nullptr) {
    set_error_from_python();
    return 1;
  }
  PyObject* shape = PyObject_GetAttrString(arr, "shape");
  PyObject* bytes = PyObject_CallMethod(arr, "tobytes", nullptr);
  Py_DECREF(arr);
  if (shape == nullptr || bytes == nullptr) {
    set_error_from_python();
    Py_XDECREF(shape);
    Py_XDECREF(bytes);
    return 1;
  }
  int nd = static_cast<int>(PyTuple_Size(shape));
  *out_ndim = nd;
  *out_shape =
      static_cast<int64_t*>(malloc(sizeof(int64_t) * (nd > 0 ? nd : 1)));
  for (int d = 0; d < nd; ++d)
    (*out_shape)[d] = PyLong_AsLongLong(PyTuple_GET_ITEM(shape, d));
  Py_ssize_t blen = PyBytes_Size(bytes);
  *out_data = static_cast<float*>(malloc(blen > 0 ? blen : 1));
  std::memcpy(*out_data, PyBytes_AsString(bytes), blen);
  Py_DECREF(shape);
  Py_DECREF(bytes);
  return 0;
}

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

void PD_Free(void* p) { free(p); }

}  // extern "C"
